"""Fleet-serving tests: rendezvous hashing, prefix-affine placement,
pressure spill, and replica death (the chaos replica-kill scenario).

The unit half runs on stub replicas (pure host logic); the chaos half
drives REAL paged decoders through the DecoderFleet and kills one
mid-stream — streams on the dead replica must fail fast with the
502-equivalent error, its keys must remap to survivors (and ONLY its
keys), and the survivors must end the episode with zero leaked KV
blocks.
"""

from __future__ import annotations

import time

import pytest

from kubeflow_tpu.serving.affinity import (
    prefix_affinity_key,
    rendezvous_order,
    rendezvous_pick,
)
from kubeflow_tpu.serving.fleet import (
    DecoderFleet,
    ReplicaUnavailableError,
)


# ---------------------------------------------------------------------------
# Rendezvous hashing
# ---------------------------------------------------------------------------


def test_affinity_key_depends_only_on_leading_tokens():
    a = prefix_affinity_key([1, 2, 3, 4, 5, 6], width=4)
    b = prefix_affinity_key([1, 2, 3, 4, 99, 98], width=4)
    c = prefix_affinity_key([1, 2, 3, 5, 5, 6], width=4)
    assert a == b          # same leading 4 tokens → same key
    assert a != c          # divergence inside the window → new key
    assert prefix_affinity_key([1, 2], width=4) == \
        prefix_affinity_key([1, 2], width=4)


def test_rendezvous_order_is_stable_and_total():
    members = [f"r{i}" for i in range(5)]
    order = rendezvous_order("key-1", members)
    assert sorted(order) == sorted(members)
    assert order == rendezvous_order("key-1", list(reversed(members)))
    assert rendezvous_pick("key-1", members) == order[0]


def test_rendezvous_membership_churn_moves_about_one_nth():
    """Scale-up moves ~1/N of keys; scale-down moves ONLY the removed
    member's keys — the property that keeps every surviving replica's
    prefix trie warm across a scale event."""
    keys = [prefix_affinity_key([i, i + 1, i * 3]) for i in range(800)]
    four = [f"r{i}" for i in range(4)]
    five = four + ["r4"]
    a4 = {k: rendezvous_pick(k, four) for k in keys}
    a5 = {k: rendezvous_pick(k, five) for k in keys}
    moved = [k for k in keys if a4[k] != a5[k]]
    # Every moved key must have moved TO the new member (not reshuffled
    # among the old ones), and the moved fraction is ~1/5.
    assert all(a5[k] == "r4" for k in moved)
    assert 0.10 < len(moved) / len(keys) < 0.33
    # Scale-down (drop r2): only r2's keys move; everyone else stays.
    three = [m for m in four if m != "r2"]
    a3 = {k: rendezvous_pick(k, three) for k in keys}
    for k in keys:
        if a4[k] != "r2":
            assert a3[k] == a4[k]
        else:
            assert a3[k] != "r2"


def test_rendezvous_failover_order_is_exclusion_stable():
    """order[1] under full membership IS the pick once order[0] is
    excluded — the spill/failover sequence never reshuffles."""
    members = [f"r{i}" for i in range(6)]
    for key in ("a", "b", "c", "d"):
        order = rendezvous_order(key, members)
        rest = [m for m in members if m != order[0]]
        assert rendezvous_order(key, rest) == order[1:]


# ---------------------------------------------------------------------------
# DecoderFleet placement on stub replicas
# ---------------------------------------------------------------------------


class _StubReplica:
    """submit/metrics/stop-shaped stub with a settable queue depth."""

    def __init__(self, depth: int = 0):
        self._active_count = depth
        self._pending: list = []
        self.submitted: list = []
        self.dead = False

    def submit(self, tokens, want, temperature=0.0, *, request_id=None):
        if self.dead:
            raise RuntimeError("decoder is stopped")
        self.submitted.append(list(tokens))
        return object()

    def metrics(self):
        return {"prefix_hits": 0, "prefix_misses": len(self.submitted)}

    def stop(self):
        pass


def test_affine_routing_is_deterministic_and_affine():
    fleet = DecoderFleet({f"r{i}": _StubReplica() for i in range(4)},
                         affinity_tokens=8)
    toks = [5, 6, 7, 8, 9]
    picks = {fleet.route(toks) for _ in range(10)}
    assert len(picks) == 1  # same prompt, same replica, always
    key = prefix_affinity_key(toks, 8)
    assert picks.pop() == rendezvous_pick(key, fleet.members())


def test_spill_under_pressure_is_deterministic_least_loaded():
    reps = {f"r{i}": _StubReplica() for i in range(4)}
    fleet = DecoderFleet(reps, affinity_tokens=8, pressure=3)
    toks = [1, 2, 3]
    primary = fleet.route(toks)
    assert fleet.spilled == 0
    # Load the affine replica past the bound: the pick spills to the
    # least-loaded live replica, deterministically.
    reps[primary]._active_count = 3
    order = rendezvous_order(prefix_affinity_key(toks, 8),
                             fleet.members())
    reps[order[1]]._active_count = 2  # next-in-order is NOT least loaded
    spill = fleet.route(toks)
    assert spill != primary
    assert spill == min(order[1:],
                        key=lambda m: (reps[m]._active_count,
                                       order.index(m)))
    assert fleet.route(toks) == spill  # stable while load is stable
    assert fleet.spilled >= 2
    # Pressure relieved → the key returns home (no sticky spill).
    reps[primary]._active_count = 0
    assert fleet.route(toks) == primary


def test_affinity_concentrates_groups_vs_random_routing():
    """Prefix-affine placement sends a whole shared-prefix group to ONE
    replica; seeded-random routing spreads it — the trie-concentration
    property the fleet bench gates with real decoders, pinned here on
    the placement alone."""
    groups = {g: [[g, g + 1, g + 2, 7] + [r] for r in range(8)]
              for g in range(20)}
    affine = DecoderFleet({f"r{i}": _StubReplica() for i in range(4)},
                          affinity_tokens=4)
    rand = DecoderFleet({f"r{i}": _StubReplica() for i in range(4)},
                        affinity_tokens=4, router="random", seed=3)
    spread = {"affine": [], "random": []}
    for g, prompts in groups.items():
        spread["affine"].append(len({affine.route(p) for p in prompts}))
        spread["random"].append(len({rand.route(p) for p in prompts}))
    assert all(n == 1 for n in spread["affine"])
    assert sum(spread["random"]) / len(spread["random"]) > 2.0


def test_submit_remaps_off_dead_replica():
    reps = {f"r{i}": _StubReplica() for i in range(3)}
    fleet = DecoderFleet(reps, affinity_tokens=4)
    toks = [9, 8, 7]
    home = fleet.route(toks)
    reps[home].dead = True
    handle = fleet.submit(toks, 4)
    assert handle.replica != home
    assert home not in fleet.live_members()
    assert fleet.remapped == 1
    # Keys whose affine replica survived keep their placement.
    order = rendezvous_order(prefix_affinity_key(toks, 4),
                             ["r0", "r1", "r2"])
    assert handle.replica == [m for m in order if m != home][0]


def test_all_dead_raises_replica_unavailable():
    reps = {"r0": _StubReplica(), "r1": _StubReplica()}
    for r in reps.values():
        r.dead = True
    fleet = DecoderFleet(reps)
    with pytest.raises(ReplicaUnavailableError) as e:
        fleet.submit([1, 2], 4)
    assert e.value.code == 502


def test_gateway_route_parses_prefix_affine_spec():
    from kubeflow_tpu.gateway.routing import routes_from_service
    from kubeflow_tpu.manifests.core import (
        GATEWAY_ROUTE_ANNOTATION,
        gateway_route,
    )

    ann = gateway_route(
        "pool", "/models/m/", "m-r0.ns:8500",
        backends=[{"service": "m-r0.ns:8500", "weight": 1},
                  {"service": "m-r1.ns:8500", "weight": 1}],
        strategy="prefix-affine", affinity_tokens=24, pressure=6)
    svc = {"metadata": {"name": "m", "annotations": ann}}
    (route,) = routes_from_service(svc)
    assert route.strategy == "prefix-affine"
    assert route.affinity_tokens == 24
    assert route.pressure == 6
    # prefix-affine without a backends pool is a misconfiguration:
    # the route is rejected, not silently direct-routed.
    bad = gateway_route("solo", "/m/", "m.ns:8500",
                        strategy="prefix-affine")
    assert routes_from_service(
        {"metadata": {"name": "m", "annotations": {
            GATEWAY_ROUTE_ANNOTATION: bad[GATEWAY_ROUTE_ANNOTATION]
        }}}) == []


# ---------------------------------------------------------------------------
# Chaos: replica death mid-stream against real decoders
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny():
    import jax

    from kubeflow_tpu.models.registry import get_model

    spec = get_model("lm-test-tiny")
    return spec, spec.init(jax.random.PRNGKey(0), spec.config)


def _decoder(tiny, **kw):
    from kubeflow_tpu.serving.continuous import ContinuousDecoder

    spec, params = tiny
    kw.setdefault("slots", 4)
    kw.setdefault("prefill_len", 16)
    kw.setdefault("max_new_tokens", 192)
    kw.setdefault("kv_layout", "paged")
    kw.setdefault("kv_block_size", 8)
    kw.setdefault("stream_timeout_s", 60.0)
    return ContinuousDecoder(params, spec.config, **kw)


def test_replica_kill_mid_stream_fails_fast_and_remaps(tiny):
    """The chaos scenario: one replica's scheduler loop dies while
    streams are in flight on it. Those streams fail FAST with the
    502-coded error (no hung clients waiting out the 60s timeout), the
    fleet excludes the replica, the dead replica's keys remap to
    survivors while survivors' keys stay put, and the survivors leak
    zero KV blocks."""
    reps = {f"r{i}": _decoder(tiny) for i in range(3)}
    fleet = DecoderFleet(reps, affinity_tokens=8)
    try:
        # Find prompts whose affine home covers every replica.
        home_of = {}
        probe = 0
        while set(home_of) != set(reps) and probe < 200:
            toks = [3 + probe % 11, 5, 7, probe % 13 + 2]
            home_of.setdefault(fleet.route(toks), toks)
            probe += 1
        assert set(home_of) == set(reps)
        victim = "r1"
        survivors = [nm for nm in reps if nm != victim]

        # Long generations in flight on every replica.
        handles = {nm: fleet.submit(toks, 192) for nm, toks in
                   home_of.items()}
        for nm, h in handles.items():
            assert h.replica == nm
        # Let decode get going, then kill the victim's scheduler the
        # ungraceful way: with the state lock held (the scheduler
        # parks at its next dispatch), poison the device state so that
        # dispatch raises and the loop's crash path (_fail_all) runs —
        # deterministically MID-stream, however fast the tiny model
        # decodes.
        stream = handles[victim].tokens(timeout=60)
        next(stream)  # stream is live
        with reps[victim]._state_lock:
            reps[victim]._state = None

        t0 = time.perf_counter()
        with pytest.raises(ReplicaUnavailableError) as err:
            for _ in stream:
                pass
        elapsed = time.perf_counter() - t0
        assert err.value.code == 502
        assert elapsed < 10, f"dead-replica stream hung {elapsed:.1f}s"
        assert victim not in fleet.live_members()

        # Survivors' streams complete untouched.
        for nm in survivors:
            res = handles[nm].result(timeout=60)
            assert len(res["tokens"]) == 192

        # The victim's keys remap to the NEXT replica in their own
        # rendezvous order; survivors' keys keep their home.
        h2 = fleet.submit(home_of[victim], 4)
        key = prefix_affinity_key(home_of[victim], 8)
        order = rendezvous_order(key, ["r0", "r1", "r2"])
        assert h2.replica == [m for m in order if m != victim][0]
        assert len(h2.result(timeout=60)["tokens"]) == 4
        for nm in survivors:
            h = fleet.submit(home_of[nm], 4)
            assert h.replica == nm
            h.result(timeout=60)  # drained before the leak check
        # Drained: zero blocks still held by any survivor slot.
        m = fleet.metrics()
        assert m["kv_blocks_in_use"] == 0
        for nm in survivors:
            assert all(not b for b in reps[nm]._slot_blocks)
        assert m["dead"] == [victim]
    finally:
        fleet.stop()


def test_prefill_replica_kill_mid_handoff(tiny):
    """Disaggregated chaos: a prefill replica's scheduler dies while
    decode streams are in flight. The handoff routed at it 502s
    fail-fast, the decode pool's streams are untouched, ONLY the dead
    replica's affinity keys remap inside the prefill pool, and neither
    pool leaks a block."""
    reps = {"p0": _decoder(tiny, role="prefill",
                           prefix_cache_slots=8, prefix_cache_min_len=8),
            "p1": _decoder(tiny, role="prefill",
                           prefix_cache_slots=8, prefix_cache_min_len=8),
            "d0": _decoder(tiny, role="decode",
                           prefix_cache_slots=8, prefix_cache_min_len=8),
            "d1": _decoder(tiny, role="decode",
                           prefix_cache_slots=8, prefix_cache_min_len=8)}
    fleet = DecoderFleet(reps, affinity_tokens=8)
    try:
        # Prompts whose affine PREFILL home covers both prefill
        # replicas (>= 10 tokens so the handoff prefix clears min_len).
        home_of = {}
        probe = 0
        while set(home_of) != {"p0", "p1"} and probe < 200:
            toks = [3 + probe % 11, 5, 7, probe % 13 + 2] + \
                [11 + probe % 3] * 8
            home_of.setdefault(fleet.route_prefill(toks), toks)
            probe += 1
        assert set(home_of) == {"p0", "p1"}
        victim, survivor = "p0", "p1"

        # Long decode streams in flight on the decode pool (submitted
        # through the two-hop while every prefill replica is healthy).
        streams = [fleet.submit(home_of[survivor][:-1] + [50 + i], 64)
                   for i in range(2)]
        assert {h.replica for h in streams} <= {"d0", "d1"}

        # Kill the victim's scheduler mid-life: poison the device state
        # under the state lock so its next dispatch raises.
        with reps[victim]._state_lock:
            reps[victim]._state = None

        # A submit whose affine prefill home is the victim: the
        # in-flight handoff fails FAST with the 502-coded error.
        t0 = time.perf_counter()
        with pytest.raises(ReplicaUnavailableError) as err:
            fleet.submit(home_of[victim], 4)
        elapsed = time.perf_counter() - t0
        assert err.value.code == 502
        assert elapsed < 10, f"dead-prefill handoff hung {elapsed:.1f}s"
        assert victim not in fleet.live_members()

        # Decode-pool streams are unaffected by the prefill death.
        for h in streams:
            assert len(h.result(timeout=120)["tokens"]) == 64

        # The victim's keys remap to the surviving prefill replica;
        # the survivor's keys never move. New submits succeed (handoff
        # rides the survivor).
        assert fleet.route_prefill(home_of[victim]) == survivor
        assert fleet.route_prefill(home_of[survivor]) == survivor
        out = fleet.submit(home_of[victim], 4)
        assert len(out.result(timeout=120)["tokens"]) == 4
        m = fleet.metrics()
        assert m["prefill_pool"] == [survivor]
        assert sorted(m["decode_pool"]) == ["d0", "d1"]
        assert m["dead"] == [victim]

        # Zero leaked blocks on BOTH pools: no slot holds blocks after
        # drain (the victim's _fail_all freed its reservations too),
        # and every surviving replica's residual refs are cache-held.
        for name, rep in reps.items():
            assert all(not blks for blks in rep._slot_blocks), name
        for name in ("p1", "d0", "d1"):
            rep = reps[name]
            with rep._prefix_lock:
                while rep.prefix_cache.evict_lru():
                    pass
            assert rep._alloc.blocks_in_use == 0, name
    finally:
        fleet.stop()


def test_fleet_metrics_aggregate_live_replicas(tiny):
    reps = {"a": _decoder(tiny), "b": _decoder(tiny)}
    fleet = DecoderFleet(reps, affinity_tokens=4)
    try:
        fleet.generate([1, 2, 3], 4, timeout=60)
        m = fleet.metrics()
        assert m["tokens_emitted"] == 4
        assert sorted(m["replicas"]) == ["a", "b"]
        assert m["live"] == ["a", "b"]
        assert m["routed"] == 1
    finally:
        fleet.stop()


def test_metrics_snapshot_consistent_under_concurrent_mark_dead():
    """PR-11 regression (tpu-lint lock-inconsistent-guard): metrics()
    iterated the mutable dead set and read the routing counters without
    the fleet lock while mark_dead() ran on caller threads — a torn
    read at best, a set-changed-size RuntimeError at worst. It now
    snapshots under the lock: live/dead always partition the
    membership."""
    import threading

    reps = {f"r{i:02d}": _StubReplica() for i in range(24)}
    fleet = DecoderFleet(reps, affinity_tokens=4)
    errors: list[Exception] = []
    stop = threading.Event()

    def hammer():
        try:
            while not stop.is_set():
                m = fleet.metrics()
                live, dead = set(m["live"]), set(m["dead"])
                assert live | dead == set(reps)
                assert not live & dead
        except Exception as e:  # noqa: BLE001 — surfaced below
            errors.append(e)

    t = threading.Thread(target=hammer, daemon=True)
    t.start()
    try:
        for name in sorted(reps)[:-1]:  # keep one live member
            fleet.mark_dead(name)
            time.sleep(0.002)
    finally:
        stop.set()
        t.join(timeout=10)
    assert not errors, errors
