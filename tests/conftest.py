"""Test configuration.

Tests run on a virtual 8-device CPU mesh (the "fake slice" — SURVEY.md §4:
the multi-node-without-hardware capability the reference lacks). The env vars
must be set before jax is first imported anywhere in the test process.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402

from kubeflow_tpu.k8s.fake import FakeApiServer  # noqa: E402


@pytest.fixture()
def api():
    """A fresh fake apiserver with the kubeflow namespace present."""
    server = FakeApiServer()
    server.ensure_namespace("kubeflow")
    server.ensure_namespace("default")
    return server
