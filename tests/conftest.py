"""Test configuration.

Tests run on a virtual 8-device CPU mesh (the "fake slice" — SURVEY.md §4:
the multi-node-without-hardware capability the reference lacks). The env vars
must be set before jax is first imported anywhere in the test process.
"""

import os

xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Force CPU even when the session points JAX at real TPU hardware. On the
# TPU-tunnel image a sitecustomize pre-imports jax with JAX_PLATFORMS=axon, so
# the env var is too late there and the config knob must be flipped
# post-import; on a plain box the env var suffices and jax stays unimported
# until a test needs it (XLA_FLAGS applies either way — the CPU backend
# initializes lazily).
import sys  # noqa: E402

if "jax" in sys.modules:
    sys.modules["jax"].config.update("jax_platforms", "cpu")
    # XLA_FLAGS is parsed once per process; if a backend already came up the
    # flag above is a no-op and the device count must go through the config
    # knob (jax>=0.5), mirroring __graft_entry__._force_cpu_mesh.
    try:
        sys.modules["jax"].config.update("jax_num_cpu_devices", 8)
    except (AttributeError, RuntimeError):
        pass  # older jax, or a backend is already live with 8 devices
else:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

import pytest  # noqa: E402

from kubeflow_tpu.k8s.fake import FakeApiServer  # noqa: E402


@pytest.fixture()
def api():
    """A fresh fake apiserver with the kubeflow namespace present."""
    server = FakeApiServer()
    server.ensure_namespace("kubeflow")
    server.ensure_namespace("default")
    return server
