"""Controller tests against the fake apiserver (the envtest tier,
SURVEY.md §4). Pod phase transitions are simulated the way envtest does —
by writing pod status directly."""

import json
import os

import pytest

from kubeflow_tpu.apis import jobs as jobs_api
from kubeflow_tpu.apis.notebooks import notebook, notebook_crd
from kubeflow_tpu.apis.profiles import profile, profile_crd
from kubeflow_tpu.operators.jobs import JobController
from kubeflow_tpu.operators.notebooks import NotebookController
from kubeflow_tpu.operators.profiles import ProfileController


def make_job(kind="JaxJob", name="train", replicas=4, **spec_extra):
    replica_types = {
        "JaxJob": {"Worker": replicas},
        "TFJob": {"Chief": 1, "PS": 2, "Worker": replicas},
        "PyTorchJob": {"Master": 1, "Worker": replicas},
        "MXNetJob": {"Scheduler": 1, "Server": 1, "Worker": replicas},
        "ChainerJob": {"Master": 1, "Worker": replicas},
        "MPIJob": {"Launcher": 1, "Worker": replicas},
    }[kind]
    return {
        "apiVersion": jobs_api.JOBS_API_VERSION,
        "kind": kind,
        "metadata": {"name": name, "namespace": "kubeflow"},
        "spec": {
            "replicaSpecs": {
                rt: {
                    "replicas": n,
                    "restartPolicy": "OnFailure",
                    "template": {"spec": {"containers": [
                        {"name": "main", "image": "train:latest"}
                    ]}},
                }
                for rt, n in replica_types.items()
            },
            **spec_extra,
        },
    }


def set_pod_phase(api, pod_name, phase, exit_code=None):
    pod = api.get("v1", "Pod", pod_name, "kubeflow")
    status = {"phase": phase}
    if exit_code is not None:
        status["containerStatuses"] = [
            {"name": "main", "state": {"terminated": {"exitCode": exit_code}}}
        ]
    pod["status"] = status
    api.update_status(pod)


@pytest.fixture()
def jaxjob_env(api):
    for crd in jobs_api.all_job_crds():
        api.apply(crd)
    ctrl = JobController(api, "JaxJob")
    return api, ctrl


def test_jaxjob_creates_gang_and_env(jaxjob_env):
    api, ctrl = jaxjob_env
    api.create(make_job(tpu={"accelerator": "v5e", "topology": "2x4"}))
    ctrl.reconcile_all()

    pods = api.list("v1", "Pod", "kubeflow")
    assert len(pods) == 4
    svc = api.get("v1", "Service", "train", "kubeflow")
    assert svc["spec"]["clusterIP"] == "None"

    pod0 = api.get("v1", "Pod", "train-worker-0", "kubeflow")
    env = {e["name"]: e["value"] for e in pod0["spec"]["containers"][0]["env"]}
    assert env["JAX_COORDINATOR_ADDRESS"] == (
        "train-worker-0.train.kubeflow:8476"
    )
    assert env["JAX_NUM_PROCESSES"] == "4"
    assert env["JAX_PROCESS_ID"] == "0"
    assert pod0["spec"]["nodeSelector"][
        "cloud.google.com/gke-tpu-accelerator"] == "v5e"
    assert pod0["spec"]["subdomain"] == "train"

    job = api.get(jobs_api.JOBS_API_VERSION, "JaxJob", "train", "kubeflow")
    assert job["status"]["state"] == "Created"
    assert job["status"]["replicaStatuses"]["worker"]["pending"] == 4


def test_jaxjob_running_then_succeeded_cleans_pods(jaxjob_env):
    api, ctrl = jaxjob_env
    api.create(make_job(replicas=2))
    ctrl.reconcile_all()
    for i in range(2):
        set_pod_phase(api, f"train-worker-{i}", "Running")
    ctrl.reconcile_all()
    job = api.get(jobs_api.JOBS_API_VERSION, "JaxJob", "train", "kubeflow")
    assert job["status"]["state"] == "Running"

    for i in range(2):
        set_pod_phase(api, f"train-worker-{i}", "Succeeded")
    ctrl.reconcile_all()
    job = api.get(jobs_api.JOBS_API_VERSION, "JaxJob", "train", "kubeflow")
    assert job["status"]["state"] == "Succeeded"
    conds = {c["type"]: c["status"] for c in job["status"]["conditions"]}
    assert conds["Succeeded"] == "True"
    # cleanPodPolicy default Running: succeeded pods stay.
    assert len(api.list("v1", "Pod", "kubeflow")) == 2


def test_jaxjob_restart_on_failure_and_backoff(jaxjob_env):
    api, ctrl = jaxjob_env
    api.create(make_job(replicas=2, runPolicy={"backoffLimit": 1}))
    ctrl.reconcile_all()
    set_pod_phase(api, "train-worker-0", "Failed")
    ctrl.reconcile_all()
    job = api.get(jobs_api.JOBS_API_VERSION, "JaxJob", "train", "kubeflow")
    assert job["status"]["restartCount"] == 1
    assert job["status"]["state"] == "Restarting"
    # Pod was recreated fresh (Pending).
    pod = api.get("v1", "Pod", "train-worker-0", "kubeflow")
    assert pod.get("status", {}).get("phase") is None

    # Second failure exceeds backoffLimit=1.
    set_pod_phase(api, "train-worker-0", "Failed")
    ctrl.reconcile_all()
    job = api.get(jobs_api.JOBS_API_VERSION, "JaxJob", "train", "kubeflow")
    assert job["status"]["state"] == "Failed"
    reasons = [c["reason"] for c in job["status"]["conditions"]
               if c["status"] == "True"]
    assert "BackoffLimitExceeded" in reasons


def test_jaxjob_never_restart_fails_job(jaxjob_env):
    api, ctrl = jaxjob_env
    job = make_job(replicas=2)
    for rs in job["spec"]["replicaSpecs"].values():
        rs["restartPolicy"] = "Never"
    api.create(job)
    ctrl.reconcile_all()
    set_pod_phase(api, "train-worker-1", "Failed")
    ctrl.reconcile_all()
    got = api.get(jobs_api.JOBS_API_VERSION, "JaxJob", "train", "kubeflow")
    assert got["status"]["state"] == "Failed"


def test_jaxjob_exitcode_policy(jaxjob_env):
    api, ctrl = jaxjob_env
    job = make_job(replicas=1)
    job["spec"]["replicaSpecs"]["Worker"]["restartPolicy"] = "ExitCode"
    api.create(job)
    ctrl.reconcile_all()
    # Exit 1 = permanent failure.
    set_pod_phase(api, "train-worker-0", "Failed", exit_code=1)
    ctrl.reconcile_all()
    got = api.get(jobs_api.JOBS_API_VERSION, "JaxJob", "train", "kubeflow")
    assert got["status"]["state"] == "Failed"


def test_jaxjob_exitcode_sigkill_restarts(jaxjob_env):
    api, ctrl = jaxjob_env
    job = make_job(replicas=1)
    job["spec"]["replicaSpecs"]["Worker"]["restartPolicy"] = "ExitCode"
    api.create(job)
    ctrl.reconcile_all()
    set_pod_phase(api, "train-worker-0", "Failed", exit_code=137)
    ctrl.reconcile_all()
    got = api.get(jobs_api.JOBS_API_VERSION, "JaxJob", "train", "kubeflow")
    assert got["status"]["state"] == "Restarting"


def test_jaxjob_invalid_spec_fails(jaxjob_env):
    api, ctrl = jaxjob_env
    bad = make_job()
    bad["spec"]["replicaSpecs"]["Worker"]["template"] = {"spec": {}}
    api.create(bad)
    ctrl.reconcile_all()
    got = api.get(jobs_api.JOBS_API_VERSION, "JaxJob", "train", "kubeflow")
    assert got["status"]["state"] == "Failed"
    assert any(c["reason"] == "InvalidSpec"
               for c in got["status"]["conditions"])


def test_jaxjob_multislice_env(jaxjob_env):
    api, ctrl = jaxjob_env
    api.create(make_job(replicas=4, tpu={"accelerator": "v5e",
                                         "numSlices": 2}))
    ctrl.reconcile_all()
    pod3 = api.get("v1", "Pod", "train-worker-3", "kubeflow")
    env = {e["name"]: e["value"] for e in pod3["spec"]["containers"][0]["env"]}
    assert env["MEGASCALE_NUM_SLICES"] == "2"
    assert env["MEGASCALE_SLICE_ID"] == "1"
    assert env["TPU_WORKER_ID"] == "1"


def test_tfjob_tf_config(api):
    for crd in jobs_api.all_job_crds():
        api.apply(crd)
    ctrl = JobController(api, "TFJob")
    api.create(make_job("TFJob", replicas=2))
    ctrl.reconcile_all()
    pod = api.get("v1", "Pod", "train-worker-1", "kubeflow")
    env = {e["name"]: e["value"] for e in pod["spec"]["containers"][0]["env"]}
    tf_config = json.loads(env["TF_CONFIG"])
    assert tf_config["task"] == {"type": "worker", "index": 1}
    assert len(tf_config["cluster"]["ps"]) == 2
    assert tf_config["cluster"]["chief"][0].endswith(":8476")
    # Chief completion defines success.
    set_pod_phase(api, "train-chief-0", "Succeeded")
    ctrl.reconcile_all()
    got = api.get(jobs_api.JOBS_API_VERSION, "TFJob", "train", "kubeflow")
    assert got["status"]["state"] == "Succeeded"


def test_pytorchjob_master_env(api):
    for crd in jobs_api.all_job_crds():
        api.apply(crd)
    ctrl = JobController(api, "PyTorchJob")
    api.create(make_job("PyTorchJob", replicas=3))
    ctrl.reconcile_all()
    pod = api.get("v1", "Pod", "train-worker-2", "kubeflow")
    env = {e["name"]: e["value"] for e in pod["spec"]["containers"][0]["env"]}
    assert env["MASTER_ADDR"] == "train-master-0.train.kubeflow"
    assert env["WORLD_SIZE"] == "4"
    assert env["RANK"] == "3"


def test_mpijob_hostfile(api):
    for crd in jobs_api.all_job_crds():
        api.apply(crd)
    ctrl = JobController(api, "MPIJob")
    api.create(make_job("MPIJob", replicas=2))
    ctrl.reconcile_all()
    pod = api.get("v1", "Pod", "train-launcher-0", "kubeflow")
    env = {e["name"]: e["value"] for e in pod["spec"]["containers"][0]["env"]}
    assert "train-worker-0.train.kubeflow slots=1" in env["MPI_HOSTFILE_CONTENT"]


def test_notebook_controller_creates_statefulset_and_status(api):
    api.apply(notebook_crd())
    ctrl = NotebookController(api)
    api.create(notebook("nb1", "kubeflow", "jax-notebook:latest",
                        tpu_chips=4, workspace_pvc="ws"))
    ctrl.reconcile_all()
    sts = api.get("apps/v1", "StatefulSet", "nb1", "kubeflow")
    assert sts["spec"]["replicas"] == 1
    main = sts["spec"]["template"]["spec"]["containers"][0]
    assert main["resources"]["limits"]["google.com/tpu"] == 4
    assert api.get("v1", "Service", "nb1", "kubeflow")

    # Simulate the pod coming up; status mirrors container state.
    pod_tmpl = sts["spec"]["template"]
    pod = {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": "nb1-0", "namespace": "kubeflow",
                     "labels": pod_tmpl["metadata"]["labels"]},
        "spec": pod_tmpl["spec"],
    }
    api.create(pod)
    set_pod_phase(api, "nb1-0", "Running")
    ctrl.reconcile_all()
    nb = api.get("kubeflow-tpu.org/v1", "Notebook", "nb1", "kubeflow")
    assert nb["status"]["readyReplicas"] == 1


def test_notebook_suspend_scales_statefulset(api):
    api.apply(notebook_crd())
    ctrl = NotebookController(api)
    api.create(notebook("nb2", "kubeflow", "jax-notebook:latest"))
    ctrl.reconcile_all()
    assert api.get("apps/v1", "StatefulSet", "nb2", "kubeflow")["spec"][
        "replicas"] == 1
    nb = api.get("kubeflow-tpu.org/v1", "Notebook", "nb2", "kubeflow")
    nb["spec"]["suspend"] = True
    api.update(nb)
    ctrl.reconcile_all()
    assert api.get("apps/v1", "StatefulSet", "nb2", "kubeflow")["spec"][
        "replicas"] == 0


def test_profile_controller_provisions_namespace_rbac_quota(api):
    api.apply(profile_crd())
    ctrl = ProfileController(api)
    api.create(profile("alice", "alice@example.com",
                       quota={"hard": {"requests.google.com/tpu": "8"}}))
    ctrl.reconcile_all()
    assert api.get("v1", "Namespace", "alice")
    role = api.get("rbac.authorization.k8s.io/v1", "Role",
                   "namespace-admin", "alice")
    assert role["rules"][0]["verbs"] == ["*"]
    binding = api.get("rbac.authorization.k8s.io/v1", "RoleBinding",
                      "namespace-admin-binding", "alice")
    assert binding["subjects"][0]["name"] == "alice@example.com"
    quota = api.get("v1", "ResourceQuota", "profile-quota", "alice")
    assert quota["spec"]["hard"]["requests.google.com/tpu"] == "8"
    prof = api.get("kubeflow-tpu.org/v1", "Profile", "alice")
    assert prof["status"]["state"] == "Ready"


def test_jaxjob_gang_restart_restarts_all_workers(jaxjob_env):
    api, ctrl = jaxjob_env
    api.create(make_job(replicas=3))
    ctrl.reconcile_all()
    for i in range(3):
        set_pod_phase(api, f"train-worker-{i}", "Running")
    ctrl.reconcile_all()
    # One worker fails retryably: surviving peers hold a dead rendezvous, so
    # the WHOLE gang must be recreated.
    set_pod_phase(api, "train-worker-1", "Failed")
    ctrl.reconcile_all()
    job = api.get(jobs_api.JOBS_API_VERSION, "JaxJob", "train", "kubeflow")
    assert job["status"]["restartCount"] == 1
    for i in range(3):
        pod = api.get("v1", "Pod", f"train-worker-{i}", "kubeflow")
        assert pod.get("status", {}).get("phase") is None, i
    reasons = [c["reason"] for c in job["status"]["conditions"]
               if c["status"] == "True"]
    assert "GangRestarting" in reasons


def test_jaxjob_gang_restart_does_not_mask_permanent_failure(jaxjob_env):
    api, ctrl = jaxjob_env
    job = make_job(replicas=2)
    for rs in job["spec"]["replicaSpecs"].values():
        rs["restartPolicy"] = "ExitCode"
    api.create(job)
    ctrl.reconcile_all()
    # worker-0 permanent (exit 1), worker-1 retryable (SIGKILL 137): the job
    # must fail, not gang-restart forever.
    set_pod_phase(api, "train-worker-0", "Failed", exit_code=1)
    set_pod_phase(api, "train-worker-1", "Failed", exit_code=137)
    ctrl.reconcile_all()
    got = api.get(jobs_api.JOBS_API_VERSION, "JaxJob", "train", "kubeflow")
    assert got["status"]["state"] == "Failed"
    reasons = [c["reason"] for c in got["status"]["conditions"]
               if c["status"] == "True"]
    assert "ReplicaFailed" in reasons


def test_jaxjob_declined_gang_restart_does_not_churn(jaxjob_env):
    api, ctrl = jaxjob_env
    job = make_job(replicas=2)
    for rs in job["spec"]["replicaSpecs"].values():
        rs["restartPolicy"] = "ExitCode"
    job["spec"]["runPolicy"] = {"backoffLimit": 0}
    api.create(job)
    ctrl.reconcile_all()
    set_pod_phase(api, "train-worker-0", "Failed", exit_code=1)    # permanent
    set_pod_phase(api, "train-worker-1", "Failed", exit_code=137)  # retryable
    ctrl.reconcile_all()
    got = api.get(jobs_api.JOBS_API_VERSION, "JaxJob", "train", "kubeflow")
    assert got["status"]["state"] == "Failed"
    reasons = [c["reason"] for c in got["status"]["conditions"]
               if c["status"] == "True"]
    # Declined gang restart: no solo pod churn, no spurious restartCount, so
    # the failure reason is ReplicaFailed (not BackoffLimitExceeded).
    assert "ReplicaFailed" in reasons
    assert got["status"].get("restartCount", 0) == 0


def test_mpi_launcher_hostfile_wait_and_command(tmp_path):
    """MPIJob launcher contract: hostfile written from the controller-shipped
    env, workers waited on, mpirun line assembled (kubectl-delivery +
    mpi-operator launcher semantics)."""
    from kubeflow_tpu.workloads.mpi_launcher import (
        build_command,
        parse_hostfile,
        wait_for_workers,
        write_hostfile,
    )

    content = "w0.job.ns slots=4\nw1.job.ns slots=4\n# comment\n"
    path = str(tmp_path / "etc" / "hostfile")
    entries = write_hostfile(content, path)
    assert entries == [("w0.job.ns", 4), ("w1.job.ns", 4)]
    assert parse_hostfile(open(path).read()) == entries

    resolved = {"w0.job.ns"}
    calls = []

    def resolve(host):
        calls.append(host)
        if host not in resolved:
            resolved.add(host)  # appears on the second poll
            raise OSError("not yet")
        return "10.0.0.1"

    wait_for_workers([h for h, _ in entries], timeout=10, poll=0.01,
                     resolve=resolve, log=lambda *a: None)
    assert calls.count("w1.job.ns") == 2  # actually polled until resolvable

    cmd = build_command(["python", "train.py"], path, entries,
                        mpirun="/usr/bin/mpirun")
    assert cmd[:5] == ["/usr/bin/mpirun", "--hostfile", path, "-np", "8"]
    assert cmd[-2:] == ["python", "train.py"]
    # No mpirun / no workers -> run the command directly.
    assert build_command(["python", "train.py"], path, [], mpirun=None) == [
        "python", "train.py"
    ]


def test_mpi_launcher_main_single_process(tmp_path, monkeypatch):
    """End to end in single-process mode: writes the hostfile and execs the
    wrapped command (no MPI runtime in the test image)."""
    import kubeflow_tpu.workloads.mpi_launcher as ml

    hostfile = str(tmp_path / "hostfile")
    monkeypatch.setenv(ml.ENV_HOSTFILE_CONTENT, "")
    monkeypatch.setattr(ml.shutil, "which", lambda _: None)
    ran = {}
    monkeypatch.setattr(ml.subprocess, "call",
                        lambda cmd: ran.setdefault("cmd", cmd) and 0 or 0)
    rc = ml.main(["--hostfile", hostfile, "--", "echo", "ok"])
    assert rc == 0
    assert ran["cmd"] == ["echo", "ok"]


def test_jaxjob_preemption_reschedules_without_burning_backoff(jaxjob_env):
    """Preemption (node reclaim) gang-reschedules under ANY restart policy
    and never counts against backoffLimit (SURVEY §5.3 elastic semantics)."""
    api, ctrl = jaxjob_env
    job = make_job(replicas=2, runPolicy={"backoffLimit": 0})
    job["spec"]["replicaSpecs"]["Worker"]["restartPolicy"] = "Never"
    api.create(job)
    ctrl.reconcile_all()
    pods = api.list("v1", "Pod", "kubeflow")
    assert len(pods) == 2

    # Node reclaimed: kubelet marks the pod Failed reason=Preempted.
    victim = pods[0]["metadata"]["name"]
    pod = api.get("v1", "Pod", victim, "kubeflow")
    pod["status"] = {"phase": "Failed", "reason": "Preempted",
                     "containerStatuses": [{"name": "main", "state": {
                         "terminated": {"exitCode": 137}}}]}
    api.update_status(pod)

    ctrl.reconcile_all()  # gang deleted
    ctrl.reconcile_all()  # gang recreated
    got = api.get(jobs_api.JOBS_API_VERSION, "JaxJob", "train", "kubeflow")
    assert got["status"].get("preemptionCount", 0) == 1
    assert got["status"].get("restartCount", 0) == 0
    assert got["status"]["state"] != "Failed"  # backoffLimit=0 untouched
    conds = {c["type"]: c["reason"] for c in got["status"]["conditions"]}
    assert conds.get("Restarting") == "GangPreempted"
    assert len(api.list("v1", "Pod", "kubeflow")) == 2  # rescheduled


def test_preemption_recognized_by_disruption_target_condition(jaxjob_env):
    """Regression: a Failed pod carrying ONLY the DisruptionTarget
    condition (no kubelet reason string) still counts as preemption —
    preemptionCount bumps, backoffLimit untouched."""
    api, ctrl = jaxjob_env
    api.create(make_job(replicas=2, runPolicy={"backoffLimit": 0}))
    ctrl.reconcile_all()
    pod = api.get("v1", "Pod", "train-worker-0", "kubeflow")
    pod["status"] = {"phase": "Failed",
                     "conditions": [{"type": "DisruptionTarget",
                                     "status": "True",
                                     "reason": "EvictionByEvictionAPI"}]}
    api.update_status(pod)
    ctrl.reconcile_all()
    ctrl.reconcile_all()
    got = api.get(jobs_api.JOBS_API_VERSION, "JaxJob", "train", "kubeflow")
    assert got["status"].get("preemptionCount", 0) == 1
    assert got["status"].get("restartCount", 0) == 0
    assert got["status"]["state"] != "Failed"


def test_preemption_recognized_by_scheduler_annotation(jaxjob_env):
    """Regression: a Failed pod whose ONLY preemption signal is the
    scheduler-set kubeflow-tpu.org/preempted-by annotation (no reason,
    no condition) is accounted as a preemption, not a workload failure —
    the contract for scheduler-initiated evictions."""
    from kubeflow_tpu.apis import scheduling as sched_api

    api, ctrl = jaxjob_env
    api.create(make_job(replicas=2, runPolicy={"backoffLimit": 0}))
    ctrl.reconcile_all()
    pod = api.get("v1", "Pod", "train-worker-0", "kubeflow")
    pod["metadata"].setdefault("annotations", {})[
        sched_api.ANN_PREEMPTED_BY] = "JaxJob/kubeflow/vip"
    api.update(pod)
    pod = api.get("v1", "Pod", "train-worker-0", "kubeflow")
    pod["status"] = {"phase": "Failed"}  # no reason, no conditions
    api.update_status(pod)
    ctrl.reconcile_all()
    ctrl.reconcile_all()
    got = api.get(jobs_api.JOBS_API_VERSION, "JaxJob", "train", "kubeflow")
    assert got["status"].get("preemptionCount", 0) == 1
    assert got["status"].get("restartCount", 0) == 0
    assert got["status"]["state"] != "Failed"  # backoffLimit=0 untouched


def test_jaxjob_unknown_phase_counts_as_gang_failure(jaxjob_env):
    """A pod stuck in Unknown (node unreachable) triggers the gang restart
    path instead of hanging the collective."""
    api, ctrl = jaxjob_env
    api.create(make_job(replicas=2))
    ctrl.reconcile_all()
    name = api.list("v1", "Pod", "kubeflow")[0]["metadata"]["name"]
    pod = api.get("v1", "Pod", name, "kubeflow")
    pod["status"] = {"phase": "Unknown",
                     "conditions": [{"type": "DisruptionTarget",
                                     "status": "True"}]}
    api.update_status(pod)
    ctrl.reconcile_all()
    ctrl.reconcile_all()
    got = api.get(jobs_api.JOBS_API_VERSION, "JaxJob", "train", "kubeflow")
    assert got["status"].get("preemptionCount", 0) == 1
    assert len(api.list("v1", "Pod", "kubeflow")) == 2


def test_slice_health_probe_runs():
    """The health probe passes on the virtual slice and fails on an
    impossible expectation."""
    import subprocess
    import sys

    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=2")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    ok = subprocess.run(
        [sys.executable, "-m", "kubeflow_tpu.workloads.slice_health",
         "--expect-local-devices", "2"],
        capture_output=True, text=True, timeout=180, env=env,
    )
    assert ok.returncode == 0, ok.stdout + ok.stderr
    report = json.loads(ok.stdout.strip().splitlines()[-1])
    assert report["healthy"] and report["psum"] == 4.0

    bad = subprocess.run(
        [sys.executable, "-m", "kubeflow_tpu.workloads.slice_health",
         "--expect-devices", "999"],
        capture_output=True, text=True, timeout=180, env=env,
    )
    assert bad.returncode == 1
    assert "999" in json.loads(bad.stdout.strip().splitlines()[-1])["error"]


def test_mpi_sidecar_follows_launcher_phase(api):
    """openmpi-controller semantics (controller.py:92-104): the worker
    sidecar exits with the launcher pod's outcome."""
    from kubeflow_tpu.workloads.mpi_sidecar import wait_for_launcher

    api.create({
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": "job-launcher-0", "namespace": "kubeflow",
                     "labels": {"kubeflow-tpu.org/job-name": "job",
                                "kubeflow-tpu.org/replica-type": "launcher"}},
        "spec": {"containers": [{"name": "l", "image": "i"}]},
        "status": {"phase": "Running"},
    })
    phases = iter(["Running", "Succeeded"])

    def tick(_):
        pod = api.get("v1", "Pod", "job-launcher-0", "kubeflow")
        pod["status"]["phase"] = next(phases)
        api.update_status(pod)

    rc = wait_for_launcher(api, "job", "kubeflow", poll_seconds=0,
                           log=lambda *a: None, sleep=tick)
    assert rc == 0

    pod = api.get("v1", "Pod", "job-launcher-0", "kubeflow")
    pod["status"]["phase"] = "Failed"
    api.update_status(pod)
    assert wait_for_launcher(api, "job", "kubeflow", poll_seconds=0,
                             log=lambda *a: None, sleep=lambda s: None) == 1
    # Launcher gone entirely -> failure after the grace polls.
    api.delete("v1", "Pod", "job-launcher-0", "kubeflow")
    assert wait_for_launcher(api, "job", "kubeflow", poll_seconds=0,
                             grace_polls=1, log=lambda *a: None,
                             sleep=lambda s: None) == 1


def test_leader_election_single_holder_and_failover(api):
    """Lease semantics: one holder at a time; standby takes over when the
    lease expires or is released (client-go leaderelection analogue)."""
    import time as _time

    from kubeflow_tpu.operators.leader import LeaderElector

    a = LeaderElector(api, name="op", identity="a", lease_seconds=1)
    b = LeaderElector(api, name="op", identity="b", lease_seconds=1)
    assert a.try_acquire() is True
    assert b.try_acquire() is False
    assert a.is_leader and not b.is_leader
    # Renewal keeps leadership.
    assert a.try_acquire() is True
    _time.sleep(0.6)
    assert a.try_acquire() is True  # renewal resets b's observation clock
    _time.sleep(0.6)
    assert b.try_acquire() is False  # 1.2s since b's first observation,
    # but only 0.6s since the record last changed — lease still healthy

    # Leader stops renewing → standby takes over after a full local
    # lease duration with no observed transition.
    _time.sleep(1.1)
    assert b.try_acquire() is True
    assert a.try_acquire() is False  # a lost it

    # Clean release: a can immediately re-acquire.
    b.release()
    assert a.try_acquire() is True


def test_leader_election_tolerates_clock_skew(api):
    """A leader on a node whose clock is minutes behind writes renewTimes
    that look expired against the local wall clock, but it renews on
    schedule — a standby must judge expiry from locally observed renewTime
    *transitions* (monotonic), never wall-clock comparison, so a healthy
    skewed leader is never seized from."""
    import datetime
    import time as _time

    from kubeflow_tpu.operators.leader import (
        LEASE_API_VERSION,
        LeaderElector,
    )

    def skewed_stamp(seconds_ago):
        return (datetime.datetime.now(datetime.timezone.utc)
                - datetime.timedelta(seconds=seconds_ago)).strftime(
                    "%Y-%m-%dT%H:%M:%S.%fZ")

    api.create({
        "apiVersion": LEASE_API_VERSION, "kind": "Lease",
        "metadata": {"name": "skew", "namespace": "kubeflow"},
        "spec": {"holderIdentity": "remote-leader",
                 "leaseDurationSeconds": 0.3,
                 "renewTime": skewed_stamp(600)},
    })
    b = LeaderElector(api, name="skew", identity="b", lease_seconds=0.3)
    # First observation starts the local clock; stamp looks 600s stale but
    # that alone must not grant the lease.
    assert b.try_acquire() is False
    # The skewed leader keeps renewing (stamp advances, still "stale").
    for seconds_ago in (599, 598):
        _time.sleep(0.2)
        lease = api.get(LEASE_API_VERSION, "Lease", "skew", "kubeflow")
        lease["spec"]["renewTime"] = skewed_stamp(seconds_ago)
        api.update(lease)
        assert b.try_acquire() is False  # record changed → leader healthy
    # Renewals stop → after a locally-observed full lease duration b leads.
    _time.sleep(0.4)
    assert b.try_acquire() is True


@pytest.mark.slow
def test_leader_elected_manager_exits_on_leadership_loss(api):
    """Split-brain guard end to end: a real manager process acquires the
    Lease over HTTP, then exits nonzero when another identity steals it
    (client-go OnStoppedLeading-is-fatal semantics)."""
    import subprocess
    import sys
    import time

    from kubeflow_tpu.apis.profiles import profile_crd
    from kubeflow_tpu.k8s.httpfake import serve

    api.apply(profile_crd())
    httpd, port = serve(api)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               KUBEFLOW_TPU_APISERVER=f"http://127.0.0.1:{port}")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.Popen(
        [sys.executable, "-m", "kubeflow_tpu.operators.profile",
         "--leader-elect", "--leader-elect-name", "smoke-lease",
         "--metrics-port", "0"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True,
    )
    try:
        lease = None
        for _ in range(150):
            lease = api.get_or_none("coordination.k8s.io/v1", "Lease",
                                    "smoke-lease", "kubeflow")
            if lease:
                break
            time.sleep(0.2)
        assert lease, "manager never acquired the lease"
        lease["spec"]["holderIdentity"] = "other"
        lease["spec"]["renewTime"] = "2099-01-01T00:00:00.000000Z"
        api.update(lease)
        assert proc.wait(timeout=60) == 1
    finally:
        if proc.poll() is None:
            proc.kill()
        httpd.shutdown()


def test_run_loop_failure_exit_stops_pumps(api):
    """PR-11 regression (tpu-lint thread-lifecycle triage): a reconcile
    loop that died by exception closed its workqueue but never set the
    stop flag — the pump threads' only termination signal — so they
    kept reopening watches and delivering events forever. ANY exit of
    run() now sets the flag and the pumps wind down."""
    ctrl = NotebookController(api)

    def boom(*a, **kw):
        raise RuntimeError("loop death")

    ctrl._queue.get = boom
    with pytest.raises(RuntimeError, match="loop death"):
        ctrl.run()
    assert ctrl._stop.is_set()
    for pump in ctrl._pumps:
        pump.join(timeout=10)
        assert not pump.is_alive(), "pump thread survived loop death"
