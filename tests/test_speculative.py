"""Speculative decoding: fused batched-verify correctness and the draft
proposers.

The load-bearing invariant is that speculation changes COST, never
output: greedy decoding with speculation on is byte-identical to
speculation off (cold and warm), and temperature>0 rows keep the target
distribution via rejection-resampling against the deterministic draft.
Alongside, the decode_chunk/retire_row interaction these paths share:
EOS mid-chunk must park a row on device exactly as host-side retirement
would.
"""

import http.client
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.observability.metrics import type_line
from kubeflow_tpu.models.decode import (
    decode_chunk,
    decode_step,
    init_decode_state,
    insert_row,
    prefill,
    retire_row,
    verify_step,
)
from kubeflow_tpu.models.registry import get_model
from kubeflow_tpu.serving.continuous import ContinuousDecoder
from kubeflow_tpu.serving.engine import EngineConfig
from kubeflow_tpu.serving.server import ModelServer
from kubeflow_tpu.serving.speculative import (
    DraftModelProposer,
    NgramProposer,
    make_proposer,
)

TOTAL = 24


@pytest.fixture(scope="module")
def model():
    spec = get_model("lm-test-tiny")
    params = spec.init(jax.random.PRNGKey(0), spec.config)
    return spec, params


def _make_state(model, prompts, want=8, temps=None, seed=0):
    spec, params = model
    st = init_decode_state(spec.config, len(prompts), TOTAL, seed)
    for i, p in enumerate(prompts):
        arr = np.zeros((1, 8), np.int32)
        arr[0, : len(p)] = p
        cache, last = prefill(params, jnp.asarray(arr),
                              jnp.asarray([len(p)], np.int32), spec.config,
                              total_len=TOTAL)
        st = insert_row(st, jnp.int32(i), cache, last, jnp.int32(len(p)),
                        jnp.int32(want), jnp.float32(
                            0.0 if temps is None else temps[i]))
    return st


def _greedy_chain(model, prompts, n=8, **kw):
    spec, params = model
    st = _make_state(model, prompts, want=n)
    out = [[] for _ in prompts]
    for _ in range(n):
        st, tok, emit = decode_step(st, params, spec.config, **kw)
        tok, emit = jax.device_get((tok, emit))
        for i in range(len(prompts)):
            if emit[i]:
                out[i].append(int(tok[i]))
    return out


# ---------------------------------------------------------------------------
# verify_step — the fused kernel
# ---------------------------------------------------------------------------


def test_verify_accepts_correct_drafts_and_stays_on_chain(model):
    """Perfect drafts: one verify emits K accepted tokens + the committed
    bonus, all equal to the plain decode chain."""
    spec, params = model
    prompts = [[1, 2, 3], [7, 5]]
    ref = _greedy_chain(model, prompts)
    st = _make_state(model, prompts)
    draft = np.asarray([r[:4] for r in ref], np.int32)
    st, out, emitted = verify_step(st, params, spec.config,
                                   jnp.asarray(draft),
                                   jnp.asarray([4, 4], np.int32))
    out, emitted = jax.device_get((out, emitted))
    for i in range(2):
        got = [int(t) for t, e in zip(out[i], emitted[i]) if e]
        assert got == ref[i][:5], (i, got, ref[i])
    # The state is live mid-chain: plain steps continue the same chain.
    for j in range(5, 8):
        st, tok, emit = decode_step(st, params, spec.config)
        tok = jax.device_get(tok)
        for i in range(2):
            assert int(tok[i]) == ref[i][j]


def test_verify_rejects_wrong_drafts_but_still_progresses(model):
    """Garbage drafts: every verify still emits exactly one correct chain
    token (the committed target sample) — progress is guaranteed."""
    spec, params = model
    prompts = [[1, 2, 3], [7, 5]]
    ref = _greedy_chain(model, prompts)
    st = _make_state(model, prompts)
    got = [[] for _ in prompts]
    for _ in range(4):
        draft = np.full((2, 4), 200, np.int32)
        st, out, emitted = verify_step(st, params, spec.config,
                                       jnp.asarray(draft),
                                       jnp.asarray([4, 4], np.int32))
        out, emitted = jax.device_get((out, emitted))
        for i in range(2):
            toks = [int(t) for t, e in zip(out[i], emitted[i]) if e]
            assert len(toks) == 1
            got[i] += toks
    for i in range(2):
        assert got[i] == ref[i][:4]


def test_verify_partial_prefix_acceptance(model):
    """Drafts right for 2 positions then wrong: verify keeps exactly the
    matching prefix plus the correction token."""
    spec, params = model
    prompts = [[1, 2, 3]]
    ref = _greedy_chain(model, prompts)
    st = _make_state(model, prompts)
    draft = np.asarray([[ref[0][0], ref[0][1], 200, 200]], np.int32)
    st, out, emitted = verify_step(st, params, spec.config,
                                   jnp.asarray(draft),
                                   jnp.asarray([4], np.int32))
    out, emitted = jax.device_get((out, emitted))
    got = [int(t) for t, e in zip(out[0], emitted[0]) if e]
    assert got == ref[0][:3]  # 2 accepted + corrected third


def test_verify_respects_remaining_budget(model):
    """A row with budget 3 emits exactly 3 tokens even when all K drafts
    would have been accepted, then goes inactive."""
    spec, params = model
    prompts = [[1, 2, 3]]
    ref = _greedy_chain(model, prompts)
    st = _make_state(model, prompts, want=3)
    draft = np.asarray([ref[0][:4]], np.int32)
    st, out, emitted = verify_step(st, params, spec.config,
                                   jnp.asarray(draft),
                                   jnp.asarray([4], np.int32))
    out, emitted = jax.device_get((out, emitted))
    got = [int(t) for t, e in zip(out[0], emitted[0]) if e]
    assert got == ref[0][:3]
    assert not bool(jax.device_get(st["active"])[0])


def test_verify_rejection_resample_excludes_draft_token(model):
    """top_k=1 makes sampling deterministic (argmax): a non-argmax draft
    must be rejected and the resampled commit must be the argmax — the
    residual-distribution path, checked exactly."""
    spec, params = model
    prompts = [[1, 2, 3], [7, 5]]
    ref = _greedy_chain(model, prompts, top_k=1)
    st = _make_state(model, prompts, temps=[0.9, 0.9])
    bad = np.asarray([[(r[0] + 1) % 256] for r in ref], np.int32)
    st, out, emitted = verify_step(st, params, spec.config,
                                   jnp.asarray(bad),
                                   jnp.asarray([1, 1], np.int32),
                                   top_k=1)
    out, emitted = jax.device_get((out, emitted))
    for i in range(2):
        got = [int(t) for t, e in zip(out[i], emitted[i]) if e]
        assert got == [ref[i][0]], (got, ref[i][0])


def test_verify_eos_in_accepted_draft_parks_row(model):
    spec, params = model
    prompts = [[1, 2, 3]]
    ref = _greedy_chain(model, prompts)
    eos = ref[0][2]
    st = _make_state(model, prompts)
    draft = np.asarray([ref[0][:4]], np.int32)
    st, out, emitted = verify_step(st, params, spec.config,
                                   jnp.asarray(draft),
                                   jnp.asarray([4], np.int32), eos_id=eos)
    out, emitted = jax.device_get((out, emitted))
    got = [int(t) for t, e in zip(out[0], emitted[0]) if e]
    assert got == ref[0][:3] and got[-1] == eos  # truncated AT the EOS
    st = jax.device_get(st)
    assert not bool(st["active"][0])
    assert int(st["length"][0]) == TOTAL  # parked like retire_row


# ---------------------------------------------------------------------------
# Proposers
# ---------------------------------------------------------------------------


def test_ngram_proposes_continuation_of_repeated_pattern():
    p = NgramProposer(max_match=3)
    # trailing [1, 2] last occurred at the start, followed by [3, 4].
    assert p._lookup([1, 2, 3, 4, 9, 1, 2], 2) == [3, 4]
    assert p._lookup([1, 2, 3, 4, 9, 1, 2], 8) == [3, 4, 9, 1, 2]


def test_ngram_prefers_longest_and_most_recent_match():
    p = NgramProposer(max_match=3)
    # trailing trigram [1,2,3] matches at position 4 (-> 8), while the
    # bigram [2,3] also matches at position 0 (-> 7): trigram wins.
    assert p._lookup([2, 3, 7, 9, 1, 2, 3, 8, 5, 1, 2, 3], 1) == [8]
    # two occurrences of the trailing bigram: the most recent one wins.
    assert p._lookup([1, 2, 5, 9, 1, 2, 6, 9, 1, 2], 1) == [6]


def test_ngram_no_match_returns_empty():
    p = NgramProposer()
    assert p._lookup([1, 2, 3, 4, 5], 4) == []
    assert p._lookup([], 4) == []
    assert p._lookup([1, 2], 0) == []


def test_draft_model_proposer_matches_target_chain(model):
    """Same weights as the target => greedy proposals ARE the target
    chain, across incremental catch-up feeds."""
    prompts = [[1, 2, 3], [7, 5]]
    ref = _greedy_chain(model, prompts)
    prop = DraftModelProposer("lm-test-tiny", 256, slots=2, total_len=TOTAL,
                              propose_steps=3)
    out = prop.propose([(0, prompts[0], 3), (1, prompts[1], 3)])
    assert out[0] == ref[0][:3] and out[1] == ref[1][:3]
    # Catch-up feed: extend contexts by the (all-accepted) chain tokens.
    out = prop.propose([(0, prompts[0] + ref[0][:3], 3),
                        (1, prompts[1] + ref[1][:3], 3)])
    assert out[0] == ref[0][3:6] and out[1] == ref[1][3:6]
    assert prop.dispatches == 2


def test_make_proposer_validates_mode_and_vocab():
    with pytest.raises(ValueError, match="draft_mode"):
        make_proposer("bogus", target_vocab=256, slots=1, total_len=8,
                      propose_steps=1)
    with pytest.raises(ValueError, match="vocab"):
        make_proposer("model:lm-test-tiny", target_vocab=999, slots=1,
                      total_len=8, propose_steps=1)


# ---------------------------------------------------------------------------
# ContinuousDecoder integration
# ---------------------------------------------------------------------------


PROMPTS = [[1, 2, 3], [7, 5], [9, 9, 9, 9, 2], [4, 1, 2, 3, 1, 2]]


def _decode_all(model, prompts, want=6, repeats=2, **kw):
    spec, params = model
    d = ContinuousDecoder(params, spec.config, slots=4, prefill_len=16,
                          max_new_tokens=8, **kw)
    try:
        rounds = []
        for _ in range(repeats):  # warm passes reuse slots + draft state
            handles = [d.submit(p, want) for p in prompts]
            rounds.append([h.result(timeout=120)["tokens"]
                           for h in handles])
        metrics = d.metrics()
    finally:
        d.stop()
    return rounds, metrics


@pytest.mark.parametrize("draft_mode", ["ngram", "model:lm-test-tiny"])
def test_speculation_is_byte_identical_cold_and_warm(model, draft_mode):
    ref, _ = _decode_all(model, PROMPTS)
    assert ref[0] == ref[1]  # the oracle itself is warm-stable
    got, m = _decode_all(model, PROMPTS, speculative_k=4,
                         draft_mode=draft_mode)
    assert got[0] == ref[0], "cold pass diverged"
    assert got[1] == ref[0], "warm pass diverged"
    if draft_mode.startswith("model:"):
        # The draft model always has proposals; n-gram drafting only
        # fires once the context repeats, which is round-timing-dependent
        # on this synthetic model — parity above is the invariant there.
        assert m["spec_verify_dispatches"] > 0


def test_model_draft_acceptance_and_dispatch_economy(model):
    """Identical draft weights: near-total acceptance, and the whole
    point — multiple accepted tokens per verify dispatch."""
    ref, m_off = _decode_all(model, PROMPTS)
    got, m = _decode_all(model, PROMPTS, speculative_k=4,
                         draft_mode="model:lm-test-tiny")
    assert got[0] == ref[0]
    assert m["spec_acceptance_rate"] > 0.9, m
    per_dispatch = m["spec_accepted_tokens"] / m["spec_verify_dispatches"]
    assert per_dispatch > 1.5, m
    assert m["decode_dispatches"] < m_off["decode_dispatches"], (m, m_off)


def test_chunked_speculation_byte_identical(model):
    ref, _ = _decode_all(model, PROMPTS)
    got, m = _decode_all(model, PROMPTS, speculative_k=3, chunk_size=2,
                         draft_mode="model:lm-test-tiny")
    assert got[0] == ref[0] and got[1] == ref[0]
    assert m["spec_acceptance_rate"] > 0.9, m


def test_speculation_with_eos_parity(model):
    spec, params = model
    ref, _ = _decode_all(model, [[1, 2, 3]], want=6)
    eos = ref[0][0][2]
    off, _ = _decode_all(model, [[1, 2, 3]], want=6, eos_id=eos)
    on, _ = _decode_all(model, [[1, 2, 3]], want=6, eos_id=eos,
                        speculative_k=4, draft_mode="model:lm-test-tiny")
    assert off[0][0] == ref[0][0][:3]
    assert on == off


def test_sampled_speculation_completes_with_budget(model):
    """temperature>0 rides rejection-resampling: requests complete with
    exactly their budget and in-vocab tokens (the distribution identity
    is pinned exactly by the top_k=1 kernel test above)."""
    spec, params = model
    d = ContinuousDecoder(params, spec.config, slots=4, prefill_len=16,
                          max_new_tokens=8, speculative_k=4,
                          draft_mode="model:lm-test-tiny")
    try:
        handles = [d.submit(p, 6, temperature=0.9) for p in PROMPTS]
        for h in handles:
            toks = h.result(timeout=120)["tokens"]
            assert len(toks) == 6
            assert all(0 <= t < 256 for t in toks)
    finally:
        d.stop()


def test_draft_length_auto_tunes_down_on_rejection(model):
    """A draft model with DIFFERENT weights keeps missing: the per-slot
    draft length must shrink below the configured K (and the decoder
    still produces byte-identical output)."""
    spec, params = model
    ref, _ = _decode_all(model, PROMPTS, want=8)
    d = ContinuousDecoder(params, spec.config, slots=4, prefill_len=16,
                          max_new_tokens=8, speculative_k=4,
                          draft_mode="model:lm-test-tiny", seed=7)
    try:
        handles = [d.submit(p, 8) for p in PROMPTS]
        toks = [h.result(timeout=120)["tokens"] for h in handles]
        m = d.metrics()
    finally:
        d.stop()
    assert toks == ref[0]
    assert m["spec_acceptance_rate"] < 0.9  # mismatched draft misses
    assert m["spec_draft_k"] < 4, m  # and the tuner backed off


def test_spec_counters_in_prometheus_export(model):
    server = ModelServer(
        EngineConfig(model="lm-test-tiny", batch_size=4, max_seq_len=16,
                     max_new_tokens=8, speculative_k=4,
                     draft_mode="model:lm-test-tiny"),
        port=0, grpc_port=None, batch_timeout_ms=2,
    )
    server.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                          timeout=60)
        conn.request(
            "POST", "/v1/models/lm-test-tiny:predict",
            body=json.dumps({"instances": [
                {"tokens": [1, 2, 3], "max_new_tokens": 6}]}).encode(),
            headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        resp.read()
        assert resp.status == 200
        conn.request("GET", "/monitoring/prometheus/metrics")
        text = conn.getresponse().read().decode()
        conn.close()
    finally:
        server.stop()
    assert type_line("serving_spec_accepted_tokens_total",
                     "counter") in text
    assert "serving_spec_drafted_tokens_total" in text
    assert "serving_spec_verify_dispatches_total" in text
    assert "serving_spec_acceptance_rate" in text


# ---------------------------------------------------------------------------
# decode_chunk × retire_row (EOS mid-chunk parks exactly like retirement)
# ---------------------------------------------------------------------------


def test_decode_chunk_eos_parks_exactly_like_retire_row(model):
    """Row 0 samples EOS mid-chunk; a separate state retires the row from
    the host at the same point. Park state must be identical, the peer
    row unaffected, and the freed slot reusable in both."""
    spec, params = model
    prompts = [[1, 2, 3], [7, 5]]
    ref = _greedy_chain(model, prompts)
    eos = ref[0][2]  # row 0's third token stops it mid-chunk

    # Path A: one fused 6-step chunk with on-device EOS parking.
    st_a = _make_state(model, prompts, want=6)
    st_a, toks_a, emits_a = decode_chunk(st_a, params, spec.config, 6,
                                         eos_id=eos)
    toks_a, emits_a = jax.device_get((toks_a, emits_a))
    row0 = [int(toks_a[k, 0]) for k in range(6) if emits_a[k, 0]]
    assert row0 == ref[0][:3]  # stopped AT the EOS, nothing leaked after

    # Path B: per-token steps, host retires row 0 when it sees the EOS.
    st_b = _make_state(model, prompts, want=6)
    for _ in range(3):
        st_b, tok_b, _e = decode_step(st_b, params, spec.config)
    assert int(jax.device_get(tok_b)[0]) == eos
    st_b = retire_row(st_b, jnp.int32(0))
    for _ in range(3):  # peer row finishes its 6 tokens
        st_b, _t, _e = decode_step(st_b, params, spec.config)

    a, b = jax.device_get((st_a, st_b))
    assert not a["active"][0] and not b["active"][0]
    assert int(a["length"][0]) == TOTAL == int(b["length"][0])  # parked
    # Peer row decoded the same chain in both paths.
    row1 = [int(toks_a[k, 1]) for k in range(6) if emits_a[k, 1]]
    assert row1 == ref[1][:6]
    assert int(a["length"][1]) == int(b["length"][1])

    # The parked slot is cleanly reusable in BOTH paths: readmit a fresh
    # prompt into row 0 and decode — identical continuations.
    arr = np.zeros((1, 8), np.int32)
    arr[0, :2] = [9, 9]
    cache, last = prefill(params, jnp.asarray(arr),
                          jnp.asarray([2], np.int32), spec.config,
                          total_len=TOTAL)
    outs = []
    for st in (st_a, st_b):
        st = insert_row(st, jnp.int32(0), cache, last, jnp.int32(2),
                        jnp.int32(4), jnp.float32(0.0))
        got = []
        for _ in range(4):
            st, tok, emit = decode_step(st, params, spec.config)
            tok, emit = jax.device_get((tok, emit))
            if emit[0]:
                got.append(int(tok[0]))
        outs.append(got)
    assert outs[0] == outs[1] and len(outs[0]) == 4


def test_decode_chunk_after_retire_emits_nothing_for_parked_row(model):
    """retire_row mid-stream, then a fused chunk: the parked row neither
    samples nor scatters (no cache corruption for the survivor)."""
    spec, params = model
    prompts = [[1, 2, 3], [7, 5]]
    ref = _greedy_chain(model, prompts)
    st = _make_state(model, prompts, want=8)
    st, _t, _e = decode_step(st, params, spec.config)
    st = retire_row(st, jnp.int32(0))
    st, toks, emits = decode_chunk(st, params, spec.config, 5)
    toks, emits = jax.device_get((toks, emits))
    assert not emits[:, 0].any()
    row1 = [int(toks[k, 1]) for k in range(5) if emits[k, 1]]
    assert row1 == ref[1][1:6]
