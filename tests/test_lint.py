"""Static-analysis gate — the test_flake8.py analogue.

The reference fails CI on any flake8 violation
(/root/reference/testing/test_flake8.py:1-40 walks the tree and asserts
zero); this repo's gate runs the platform's own AST linter
(kubeflow_tpu/utils/lint.py) over every Python file. A violation anywhere
fails the suite.
"""

import textwrap
from pathlib import Path

from kubeflow_tpu.utils import lint

REPO = Path(__file__).resolve().parent.parent


def test_repo_is_lint_clean():
    violations = lint.lint_tree(
        REPO / "kubeflow_tpu", REPO / "tests",
        REPO / "bench.py", REPO / "bench_serving.py",
        REPO / "__graft_entry__.py", REPO / "docs",
    )
    assert not violations, "\n".join(str(v) for v in violations)


def _lint_source(tmp_path, source, name="mod.py"):
    f = tmp_path / name
    f.write_text(textwrap.dedent(source))
    return {v.code for v in lint.lint_file(f)}


def test_linter_catches_each_class(tmp_path):
    assert "E999" in _lint_source(tmp_path, "def broken(:\n")
    assert "E501" in _lint_source(
        tmp_path, '"""doc."""\nx = "%s"\n' % ("a" * 120))
    assert "W291" in _lint_source(tmp_path, '"""doc."""\nx = 1   \n')
    assert "F401" in _lint_source(tmp_path, '"""doc."""\nimport os\n')
    assert "E711" in _lint_source(
        tmp_path, '"""doc."""\ny = 1\nx = y == None\n')
    assert "E722" in _lint_source(
        tmp_path,
        '"""doc."""\ntry:\n    pass\nexcept:\n    pass\n')
    assert "D100" in _lint_source(tmp_path, "x = 1\n")


def test_linter_exemptions(tmp_path):
    # __future__ imports, noqa lines, used imports, __init__ re-exports.
    assert not _lint_source(
        tmp_path,
        '"""doc."""\nfrom __future__ import annotations\n'
        "import os\nprint(os.sep)\n",
    )
    assert "F401" not in _lint_source(
        tmp_path, '"""doc."""\nimport os  # noqa\n')
    assert "F401" not in _lint_source(
        tmp_path, '"""doc."""\nfrom os import sep\n', name="__init__.py")
    assert "E501" not in _lint_source(
        tmp_path,
        '"""doc."""\n# see https://example.com/%s\n' % ("a" * 120))
