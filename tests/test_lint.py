"""Static-analysis gate — the test_flake8.py analogue.

The reference fails CI on any flake8 violation
(/root/reference/testing/test_flake8.py:1-40 walks the tree and asserts
zero); this repo's gate runs the platform's own AST linter
(kubeflow_tpu/utils/lint.py) over every Python file. A violation anywhere
fails the suite.
"""

import textwrap
from pathlib import Path

from kubeflow_tpu.utils import lint

REPO = Path(__file__).resolve().parent.parent


def test_repo_is_lint_clean():
    violations = lint.lint_tree(
        REPO / "kubeflow_tpu", REPO / "tests",
        REPO / "bench.py", REPO / "bench_serving.py",
        REPO / "__graft_entry__.py", REPO / "docs",
    )
    assert not violations, "\n".join(str(v) for v in violations)


def _lint_source(tmp_path, source, name="mod.py"):
    f = tmp_path / name
    f.write_text(textwrap.dedent(source))
    return {v.code for v in lint.lint_file(f)}


def test_linter_catches_each_class(tmp_path):
    assert "E999" in _lint_source(tmp_path, "def broken(:\n")
    assert "E501" in _lint_source(
        tmp_path, '"""doc."""\nx = "%s"\n' % ("a" * 120))
    assert "W291" in _lint_source(tmp_path, '"""doc."""\nx = 1   \n')
    assert "F401" in _lint_source(tmp_path, '"""doc."""\nimport os\n')
    assert "E711" in _lint_source(
        tmp_path, '"""doc."""\ny = 1\nx = y == None\n')
    assert "E722" in _lint_source(
        tmp_path,
        '"""doc."""\ntry:\n    pass\nexcept:\n    pass\n')
    assert "D100" in _lint_source(tmp_path, "x = 1\n")


def test_linter_exemptions(tmp_path):
    # __future__ imports, noqa lines, used imports, __init__ re-exports.
    assert not _lint_source(
        tmp_path,
        '"""doc."""\nfrom __future__ import annotations\n'
        "import os\nprint(os.sep)\n",
    )
    assert "F401" not in _lint_source(
        tmp_path, '"""doc."""\nimport os  # noqa\n')
    assert "F401" not in _lint_source(
        tmp_path, '"""doc."""\nfrom os import sep\n', name="__init__.py")
    assert "E501" not in _lint_source(
        tmp_path,
        '"""doc."""\n# see https://example.com/%s\n' % ("a" * 120))


def test_linter_catches_round4_classes(tmp_path):
    # F821: a typo'd/undefined name.
    assert "F821" in _lint_source(
        tmp_path, '"""doc."""\nx = 1\nprint(xy)\n')
    # F841: assigned, never read.
    assert "F841" in _lint_source(
        tmp_path,
        '"""doc."""\ndef f():\n    unused = 3\n    return 1\n')
    # A001: builtin shadowed in a name scope.
    assert "A001" in _lint_source(
        tmp_path, '"""doc."""\ndef f(list):\n    return list\n')
    assert "A001" in _lint_source(
        tmp_path, '"""doc."""\ndef f():\n    id = 3\n    return id\n')


def test_round4_exemptions(tmp_path):
    # F821 never fires on conditionally-bound, builtin, dunder, or
    # star-imported names.
    assert "F821" not in _lint_source(
        tmp_path,
        '"""doc."""\nimport os\nif os.sep:\n    maybe = 1\n'
        "print(maybe, __name__, len([]))\n")
    assert "F821" not in _lint_source(
        tmp_path, '"""doc."""\nfrom os.path import *\nprint(join)\n')
    # F841 skips _-prefixed, tuple unpacking, and closure-read locals.
    assert "F841" not in _lint_source(
        tmp_path,
        '"""doc."""\ndef f():\n    _scratch = 3\n    a, b = 1, 2\n'
        "    used = 5\n    def g():\n        return used\n    return g\n")
    # A001 exempts class attributes and methods (self.-scoped, the A003
    # family) and self/cls.
    assert "A001" not in _lint_source(
        tmp_path,
        '"""doc."""\nclass C:\n    type = "x"\n'
        "    def list(self):\n        return self.type\n")
    # Class-body assignment inside a factory fn is not the fn's local.
    assert "F841" not in _lint_source(
        tmp_path,
        '"""doc."""\ndef make():\n    class H:\n        version = 1\n'
        "    return H\n")


def test_a001_catches_import_and_except_bindings(tmp_path):
    assert "A001" in _lint_source(
        tmp_path, '"""doc."""\nimport functools as list\nprint(list)\n')
    assert "A001" in _lint_source(
        tmp_path,
        '"""doc."""\ntry:\n    pass\n'
        "except Exception as list:\n    print(list)\n")


def test_f841_reports_first_assignment_line(tmp_path):
    f = tmp_path / "mod.py"
    f.write_text('"""doc."""\ndef f():\n    x = 1\n    x = 2\n')
    v = [v for v in lint.lint_file(f) if v.code == "F841"]
    assert v and v[0].line == 3  # the FIRST binding, not the last
