"""Multi-process distributed rendezvous tests (SURVEY.md §4's mandate).

Tier "fake slice": N real OS processes each perform the
``jax.distributed.initialize`` rendezvous through the exact code path a
JaxJob worker runs in production (`initialize_from_env` with the
operator-injected env), form a global device mesh over per-process virtual
CPU devices, and run a psum — the capability the reference can only test by
provisioning a real cluster (testing/install_minikube.sh,
testing/deploy_kubeflow.py:49).

The E2E test goes one layer up: a JaxJob submitted to the fake apiserver,
reconciled by the real JobController, executed by the FakeKubelet as real
subprocesses, completing through to the job's Succeeded condition — the
in-process analogue of testing/tf_job_simple_test.py.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys

import pytest

from kubeflow_tpu.apis import jobs as jobs_api
from kubeflow_tpu.k8s.kubelet import FakeKubelet
from kubeflow_tpu.operators.jobs import JobController

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def worker_env(port: int, num: int, pid: int, devices: int) -> dict:
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # no real-TPU plumbing in workers
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": f"--xla_force_host_platform_device_count={devices}",
        jobs_api.ENV_COORDINATOR_ADDRESS: f"127.0.0.1:{port}",
        jobs_api.ENV_NUM_PROCESSES: str(num),
        jobs_api.ENV_PROCESS_ID: str(pid),
        "PYTHONPATH": REPO,
    })
    return env


def test_kubelet_verbose_pod_does_not_deadlock(api):
    """A pod writing far more than the OS pipe buffer (~64KB) must still
    run to completion — stdout spools to a file, so a verbose-but-healthy
    workload can't block on write and get killed at the timeout."""
    api.create({
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": "chatty", "namespace": "kubeflow"},
        "spec": {"containers": [{
            "name": "main",
            "command": ["python", "-c",
                        "import sys\n"
                        "for _ in range(4000):\n"
                        "    sys.stdout.write('x' * 256 + '\\n')\n"
                        "print('done')"],
        }]},
        "status": {"phase": "Pending"},
    })
    kubelet = FakeKubelet(api, timeout=30)
    try:
        kubelet.run_until_idle(deadline=30)
    finally:
        kubelet.shutdown()
    pod = api.get("v1", "Pod", "chatty", "kubeflow")
    assert pod["status"]["phase"] == "Succeeded", pod["status"]
    assert "done" in pod["status"].get("log", "")


@pytest.mark.slow
def test_two_process_rendezvous_psum():
    """2 processes × 2 CPU devices rendezvous and psum over all 4 devices."""
    port = free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "kubeflow_tpu.workloads.allreduce_smoke",
             "--value", "1.5"],
            env=worker_env(port, 2, pid, devices=2),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            cwd=REPO,
        )
        for pid in range(2)
    ]
    outs = [p.communicate(timeout=180)[0] for p in procs]
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out
    # Every process saw the global slice and the full-reduction value.
    reports = [json.loads(out.strip().splitlines()[-1]) for out in outs]
    for rep in reports:
        assert rep["global_devices"] == 4, rep
        assert rep["local_devices"] == 2, rep
        assert rep["psum"] == pytest.approx(1.5 * 4), rep
    assert sorted(r["process_id"] for r in reports) == [0, 1]


@pytest.mark.slow
def test_jaxjob_e2e_fake_slice(api):
    """JaxJob → controller gang → FakeKubelet subprocesses → Succeeded."""
    for crd in jobs_api.all_job_crds():
        api.apply(crd)
    ctrl = JobController(api, "JaxJob")
    job = {
        "apiVersion": jobs_api.JOBS_API_VERSION,
        "kind": "JaxJob",
        "metadata": {"name": "smoke", "namespace": "kubeflow"},
        "spec": {
            "replicaSpecs": {
                "Worker": {
                    "replicas": 2,
                    "restartPolicy": "Never",
                    "template": {"spec": {"containers": [{
                        "name": "main",
                        "image": "kubeflow-tpu/worker:latest",
                        "command": [
                            "python", "-m",
                            "kubeflow_tpu.workloads.allreduce_smoke",
                        ],
                    }]}},
                },
            },
        },
    }
    api.create(job)
    kubelet = FakeKubelet(api, cpu_devices_per_pod=2)
    try:
        ctrl.reconcile_all()
        pods = api.list("v1", "Pod", namespace="kubeflow")
        assert len(pods) == 2
        # The controller injected the rendezvous env the workers consume.
        env0 = {e["name"]: e["value"]
                for e in pods[0]["spec"]["containers"][0]["env"]}
        assert env0[jobs_api.ENV_NUM_PROCESSES] == "2"
        kubelet.run_until_idle(reconcile=ctrl.reconcile_all)
    finally:
        kubelet.shutdown()
    ctrl.reconcile_all()
    got = api.get(jobs_api.JOBS_API_VERSION, "JaxJob", "smoke", "kubeflow")
    conds = {c["type"]: c["status"] for c in got["status"]["conditions"]}
    assert conds.get(jobs_api.COND_SUCCEEDED) == "True", got["status"]
    # Worker logs made it into pod status (the kubectl-logs analogue).
    pod = api.get("v1", "Pod", pods[0]["metadata"]["name"], "kubeflow")
    assert '"ok": true' in pod["status"]["log"]


def make_compat_job(kind, replica_types, name="compat"):
    return {
        "apiVersion": jobs_api.JOBS_API_VERSION,
        "kind": kind,
        "metadata": {"name": name, "namespace": "kubeflow"},
        "spec": {"replicaSpecs": replica_types},
    }


@pytest.mark.slow
def test_tfjob_tf_cnn_workload_trains(api):
    """A TFJob of the tf_cnn workload (the reference's perf workload,
    tf-controller-examples/tf-cnn) trains to completion through the fake
    kubelet — VERDICT r1 weak #8's done-criterion for the compat kinds."""
    for crd in jobs_api.all_job_crds():
        api.apply(crd)
    ctrl = JobController(api, "TFJob")
    api.create(make_compat_job("TFJob", {
        "Worker": {
            "replicas": 1,
            "restartPolicy": "Never",
            "template": {"spec": {"containers": [{
                "name": "main", "image": "i",
                "command": ["python", "-m", "kubeflow_tpu.workloads.tf_cnn",
                            "--model", "resnet-test-tiny",
                            "--batch-size", "4", "--steps", "2",
                            "--data", "1"],
            }]}},
        },
    }))
    kubelet = FakeKubelet(api, cpu_devices_per_pod=1)
    try:
        ctrl.reconcile_all()
        kubelet.run_until_idle(reconcile=ctrl.reconcile_all)
    finally:
        kubelet.shutdown()
    ctrl.reconcile_all()
    job = api.get(jobs_api.JOBS_API_VERSION, "TFJob", "compat", "kubeflow")
    assert job["status"]["state"] == "Succeeded", job["status"]
    pod = api.list("v1", "Pod", "kubeflow")[0]
    assert '"samples_per_sec"' in pod["status"]["log"]


@pytest.mark.slow
def test_pytorchjob_ddp_workload_trains(api):
    """A 2-process PyTorchJob runs real torch.distributed gloo DDP through
    the operator-injected MASTER_ADDR/RANK env and succeeds."""
    for crd in jobs_api.all_job_crds():
        api.apply(crd)
    ctrl = JobController(api, "PyTorchJob")
    template = {"spec": {"containers": [{
        "name": "main", "image": "i",
        "command": ["python", "-m",
                    "kubeflow_tpu.workloads.torch_xla_ddp",
                    "--steps", "2"],
    }]}}
    api.create(make_compat_job("PyTorchJob", {
        "Master": {"replicas": 1, "restartPolicy": "Never",
                   "template": template},
        "Worker": {"replicas": 1, "restartPolicy": "Never",
                   "template": template},
    }))
    kubelet = FakeKubelet(api, cpu_devices_per_pod=1)
    try:
        ctrl.reconcile_all()
        kubelet.run_until_idle(reconcile=ctrl.reconcile_all)
    finally:
        kubelet.shutdown()
    ctrl.reconcile_all()
    job = api.get(jobs_api.JOBS_API_VERSION, "PyTorchJob", "compat",
                  "kubeflow")
    assert job["status"]["state"] == "Succeeded", job["status"]


def _run_compat_job(api, kind, replica_specs):
    for crd in jobs_api.all_job_crds():
        api.apply(crd)
    ctrl = JobController(api, kind)
    api.create(make_compat_job(kind, replica_specs))
    kubelet = FakeKubelet(api, cpu_devices_per_pod=1)
    try:
        ctrl.reconcile_all()
        kubelet.run_until_idle(reconcile=ctrl.reconcile_all)
    finally:
        kubelet.shutdown()
    ctrl.reconcile_all()
    job = api.get(jobs_api.JOBS_API_VERSION, kind, "compat", "kubeflow")
    assert job["status"]["state"] == "Succeeded", job["status"]
    reports = []
    for pod in api.list("v1", "Pod", "kubeflow"):
        log = pod["status"].get("log", "")
        reports.append(json.loads(log.strip().splitlines()[-1]))
    return reports


def _tmpl(module, *extra):
    return {"spec": {"containers": [{
        "name": "main", "image": "i",
        "command": ["python", "-m", module, *extra],
    }]}}


@pytest.mark.slow
def test_mxnetjob_parameter_server_trains(api):
    """A full DMLC gang (scheduler + 2 servers + 2 workers) trains linear
    regression through a real push/pull parameter-server protocol,
    rendezvousing via the operator-injected DMLC_* env only — VERDICT r2
    missing #7's done-criterion for MXNetJob."""
    tmpl = _tmpl("kubeflow_tpu.workloads.mxnet_ps", "--steps", "25")
    reports = _run_compat_job(api, "MXNetJob", {
        "Scheduler": {"replicas": 1, "restartPolicy": "Never",
                      "template": tmpl},
        "Server": {"replicas": 2, "restartPolicy": "Never",
                   "template": tmpl},
        "Worker": {"replicas": 2, "restartPolicy": "Never",
                   "template": tmpl},
    })
    by_role = {}
    for rep in reports:
        by_role.setdefault(rep["role"], []).append(rep)
    assert len(by_role["server"]) == 2
    assert all(s["pushes"] > 0 for s in by_role["server"])
    workers = by_role["worker"]
    assert len(workers) == 2
    for w in workers:
        assert w["converged"], w
    assert by_role["scheduler"][0]["workers_finalized"] == 2


@pytest.mark.slow
def test_chainerjob_allreduce_trains(api):
    """Master + 2 workers run synchronous star-allreduce SGD through the
    operator-injected CHAINERMN_* env and all converge on the same
    model."""
    tmpl = _tmpl("kubeflow_tpu.workloads.chainermn_train", "--steps", "25")
    reports = _run_compat_job(api, "ChainerJob", {
        "Master": {"replicas": 1, "restartPolicy": "Never",
                   "template": tmpl},
        "Worker": {"replicas": 2, "restartPolicy": "Never",
                   "template": tmpl},
    })
    assert len(reports) == 3
    ranks = sorted(rep["rank"] for rep in reports)
    assert ranks == [0, 1, 2]
    for rep in reports:
        assert rep["num_processes"] == 3
        assert rep["converged"], rep


@pytest.mark.slow
def test_jaxjob_multislice_e2e_fake_slices(api):
    """A numSlices=2 JaxJob: the controller injects the MEGASCALE env
    (coordinator address, slice id/count), the FakeKubelet rewrites the
    DCN coordinator to loopback, and every worker CONSUMES it — builds
    the hybrid DCN-mapped mesh (slices span the data axis) and reduces
    across slices (VERDICT r3 #3: the multislice path, executed)."""
    for crd in jobs_api.all_job_crds():
        api.apply(crd)
    ctrl = JobController(api, "JaxJob")
    api.create({
        "apiVersion": jobs_api.JOBS_API_VERSION,
        "kind": "JaxJob",
        "metadata": {"name": "multislice", "namespace": "kubeflow"},
        "spec": {
            "tpu": {"numSlices": 2},
            "replicaSpecs": {
                "Worker": {
                    "replicas": 2,
                    "restartPolicy": "Never",
                    "template": {"spec": {"containers": [{
                        "name": "main",
                        "image": "kubeflow-tpu/worker:latest",
                        "command": [
                            "python", "-m",
                            "kubeflow_tpu.workloads.allreduce_smoke",
                            "--value", "2.0",
                        ],
                    }]}},
                },
            },
        },
    })
    kubelet = FakeKubelet(api, cpu_devices_per_pod=2)
    try:
        ctrl.reconcile_all()
        pods = api.list("v1", "Pod", namespace="kubeflow")
        assert len(pods) == 2
        envs = [{e["name"]: e["value"]
                 for e in p["spec"]["containers"][0]["env"]} for p in pods]
        for env in envs:
            assert env[jobs_api.ENV_NUM_SLICES] == "2"
            assert "MEGASCALE_COORDINATOR_ADDRESS" in env
        assert sorted(e[jobs_api.ENV_SLICE_ID] for e in envs) == ["0", "1"]
        kubelet.run_until_idle(reconcile=ctrl.reconcile_all)
    finally:
        kubelet.shutdown()
    ctrl.reconcile_all()
    got = api.get(jobs_api.JOBS_API_VERSION, "JaxJob", "multislice",
                  "kubeflow")
    conds = {c["type"]: c["status"] for c in got["status"]["conditions"]}
    assert conds.get(jobs_api.COND_SUCCEEDED) == "True", got["status"]
    # Worker logs prove the hybrid-mesh reduction ran: 4 devices × 2.0
    # summed over the DCN-split data axis, and the MEGASCALE coordinator
    # was consumed (present in the worker's own environment report).
    for pod in pods:
        log = api.get("v1", "Pod", pod["metadata"]["name"],
                      "kubeflow")["status"]["log"]
        rep = json.loads(log.strip().splitlines()[-1])
        assert rep["ok"], rep
        assert rep["num_slices"] == 2
        assert rep["dcn_psum"] == pytest.approx(8.0)
        assert rep["hybrid_mesh_data_degree"] == 4
        assert rep["megascale_coordinator"].startswith("127.0.0.1")


def _losses_from_log(log: str) -> dict[int, float]:
    out = {}
    for line in log.splitlines():
        if line.startswith("step=") and "loss=" in line:
            parts = dict(kv.split("=") for kv in line.split() if "=" in kv)
            out[int(parts["step"])] = float(parts["loss"])
    return out


def _train_job(name: str, run_cfg: dict) -> dict:
    return {
        "apiVersion": jobs_api.JOBS_API_VERSION,
        "kind": "JaxJob",
        "metadata": {"name": name, "namespace": "kubeflow"},
        "spec": {
            "runPolicy": {"backoffLimit": 0},
            "replicaSpecs": {
                "Worker": {
                    "replicas": 1,
                    "restartPolicy": "Never",
                    "template": {"spec": {"containers": [{
                        "name": "main",
                        "image": "kubeflow-tpu/worker:latest",
                        "command": ["python", "-m",
                                    "kubeflow_tpu.train.loop",
                                    json.dumps(run_cfg)],
                    }]}},
                },
            },
        },
    }


@pytest.mark.slow
def test_preemption_resume_e2e_continues_loss_trajectory(api, tmp_path):
    """SURVEY §5.3's restart-from-checkpoint mandate, end to end: a
    checkpointing JaxJob is PREEMPTED mid-training (node-pressure
    eviction through the kubelet), the gang reschedules without burning
    backoffLimit, and the resumed worker restores the latest checkpoint
    and continues — with a loss trajectory identical to an uninterrupted
    control run on every post-resume step (state-exact + data-exact)."""
    import time as time_mod

    from kubeflow_tpu.train import checkpoint as ckpt_lib

    for crd in jobs_api.all_job_crds():
        api.apply(crd)
    ctrl = JobController(api, "JaxJob")
    base = {
        "model": "lm-test-tiny",
        "model_overrides": {"n_layers": 4, "d_model": 128, "d_ff": 256},
        "steps": 250, "log_every": 1, "batch_size": 8, "seq_len": 64,
        "checkpoint_every": 10, "seed": 5,
    }

    # Control: the same run, uninterrupted.
    api.create(_train_job(
        "control", base | {"checkpoint_dir": str(tmp_path / "control")}))
    kubelet = FakeKubelet(api, cpu_devices_per_pod=1, timeout=300)
    try:
        ctrl.reconcile_all()
        kubelet.run_until_idle(reconcile=ctrl.reconcile_all, deadline=300)
        ctl_pod = api.list("v1", "Pod", namespace="kubeflow")[0]
        control = _losses_from_log(
            api.get("v1", "Pod", ctl_pod["metadata"]["name"],
                    "kubeflow")["status"]["log"])
        assert control.get(250) is not None, "control never reached step 250"

        # Interrupted run: evict the worker once its first checkpoint
        # lands on disk (so the preemption is provably mid-training).
        ck = str(tmp_path / "train")
        api.create(_train_job("train", base | {"checkpoint_dir": ck}))
        ctrl.reconcile_all()
        victim = [p["metadata"]["name"]
                  for p in api.list("v1", "Pod", namespace="kubeflow")
                  if p["metadata"]["name"].startswith("train-")][0]
        deadline = time_mod.monotonic() + 240
        while time_mod.monotonic() < deadline:
            kubelet.step()
            if (ckpt_lib.latest_step(ck) or 0) >= 10:
                break
            time_mod.sleep(0.02)
        else:
            pytest.fail("first checkpoint never appeared")
        assert kubelet.evict(victim, "kubeflow", grace_seconds=60), (
            "job finished before the eviction window — preemption was "
            "not mid-training")
        # Graceful preemption: the worker spent its grace window saving a
        # final checkpoint at the EVICTION step (not the last periodic
        # one) — capture its log before the controller replaces the pod.
        evicted_log = api.get("v1", "Pod", victim,
                              "kubeflow")["status"]["log"]
        assert "preempted: checkpoint saved at step" in evicted_log
        preempt_step = int(
            evicted_log.split("preempted: checkpoint saved at step")[1]
            .split()[0])
        assert preempt_step > 10  # strictly past the periodic checkpoint

        kubelet.run_until_idle(reconcile=ctrl.reconcile_all, deadline=300)
    finally:
        kubelet.shutdown()
    ctrl.reconcile_all()

    got = api.get(jobs_api.JOBS_API_VERSION, "JaxJob", "train", "kubeflow")
    conds = {c["type"]: c["status"] for c in got["status"]["conditions"]}
    assert conds.get(jobs_api.COND_SUCCEEDED) == "True", got["status"]
    assert got["status"].get("preemptionCount", 0) == 1
    assert got["status"].get("restartCount", 0) == 0  # backoffLimit=0 kept

    resumed_pod = [p for p in api.list("v1", "Pod", namespace="kubeflow")
                   if p["metadata"]["name"].startswith("train-")][0]
    log = api.get("v1", "Pod", resumed_pod["metadata"]["name"],
                  "kubeflow")["status"]["log"]
    assert "resumed from checkpoint step" in log
    resume_step = int(log.split("resumed from checkpoint step")[1].split()[0])
    # SURVEY §5.3 completed: the resumed run continues from the step the
    # eviction interrupted — zero completed steps were discarded.
    assert resume_step == preempt_step

    resumed = _losses_from_log(log)
    compared = 0
    for step, loss in resumed.items():
        assert step > resume_step
        assert loss == pytest.approx(control[step], abs=2e-4), (
            f"step {step}: resumed {loss} vs control {control[step]}")
        compared += 1
    assert compared >= 50  # a real trajectory, not a fragment
    assert resumed.get(250) == pytest.approx(control[250], abs=2e-4)


def test_global_min_int_agrees_across_staggered_gang():
    """The elastic reshard agreement primitive, isolated: two real
    processes run the same global_min_int sequence; one observes the
    resize target (4) at round 2, the other at round 5. The all-reduced
    value is identical everywhere, so BOTH act on the target at round 2
    — the earliest observer wins for the whole gang (same earliest-
    signal-wins shape as the SIGTERM agreement), which is what lets the
    gang reshard in lockstep however the placement poll staggers."""
    sentinel = 2**31 - 1
    port = free_port()
    prog = (
        "import os\n"
        "from kubeflow_tpu.parallel.distributed import ("
        "global_min_int, initialize_from_env, shutdown)\n"
        "initialize_from_env()\n"
        "see_at = int(os.environ['SEE_AT'])\n"
        "first = -1\n"
        "for round_id in range(8):\n"
        f"    local = 4 if round_id >= see_at else {sentinel}\n"
        "    agreed = global_min_int(local)\n"
        f"    if agreed < {sentinel} and first < 0:\n"
        "        first = round_id\n"
        "print('FIRST_AGREED=' + str(first))\n"
        "shutdown()\n"
    )
    procs = []
    for pid, see_at in ((0, 2), (1, 5)):
        env = worker_env(port, 2, pid, devices=1)
        env["SEE_AT"] = str(see_at)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", prog], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            cwd=REPO,
        ))
    outs = [p.communicate(timeout=120)[0] for p in procs]
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out
        assert "FIRST_AGREED=2" in out, out


def test_global_any_agrees_across_staggered_gang():
    """The stop-flag agreement primitive (ADVICE r5 #2), isolated: two
    real processes join the rendezvous and run the same global_any
    sequence; one raises its local flag at round 3, the other at round
    6. BOTH must observe the first True at round 3 — the earliest
    signal wins everywhere, which is what lets the train loop break at
    one common step. Coordination-service based, so this runs on the
    plain CPU fake gang (no cross-process XLA needed)."""
    port = free_port()
    prog = (
        "import os\n"
        "from kubeflow_tpu.parallel.distributed import ("
        "global_any, initialize_from_env, shutdown)\n"
        "initialize_from_env()\n"
        "flag_at = int(os.environ['FLAG_AT'])\n"
        "first_true = -1\n"
        "for round_id in range(8):\n"
        "    agreed = global_any(round_id >= flag_at)\n"
        "    if agreed and first_true < 0:\n"
        "        first_true = round_id\n"
        "print('FIRST_TRUE=' + str(first_true))\n"
        "shutdown()\n"
    )
    procs = []
    for pid, flag_at in ((0, 3), (1, 6)):
        env = worker_env(port, 2, pid, devices=1)
        env["FLAG_AT"] = str(flag_at)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", prog], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            cwd=REPO,
        ))
    outs = [p.communicate(timeout=120)[0] for p in procs]
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out
        assert "FIRST_TRUE=3" in out, out


@pytest.mark.slow
def test_gang_preemption_checkpoints_common_step(tmp_path):
    """ADVICE r5 #2: kubelet evictions deliver SIGTERM per pod at
    different times, but orbax's save is a collective — the loop
    all-reduces the stop flag every step, so BOTH gang members break at
    the SAME step and the grace-window checkpoint commits at one common
    step instead of deadlocking the save barrier until SIGKILL. The
    stagger below lands the second SIGTERM well after the first; the
    all-reduce (not the signal) is what stops process 1."""
    import signal
    import time as time_mod

    from kubeflow_tpu.train import checkpoint as ckpt_lib

    port = free_port()
    ck = str(tmp_path / "ck")
    cfg = {"model": "lm-test-tiny", "batch_size": 4, "seq_len": 16,
           "steps": 20000, "log_every": 1, "checkpoint_dir": ck,
           "checkpoint_every": 1000000, "checkpoint_async": False,
           "mesh": {"data": 4}, "prefetch": 2, "seed": 3}
    envs = []
    for pid in range(2):
        env = worker_env(port, 2, pid, devices=2)
        env["PYTHONUNBUFFERED"] = "1"  # prompt step lines for the trigger
        envs.append(env)
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "kubeflow_tpu.train.loop",
             json.dumps(cfg)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, cwd=REPO,
        )
        for env in envs
    ]
    try:
        # Wait for real training progress on worker 0, then stagger.
        deadline = time_mod.monotonic() + 240
        lines0 = []
        for line in procs[0].stdout:
            lines0.append(line)
            if line.startswith("step=3 "):
                break
            assert time_mod.monotonic() < deadline, "".join(lines0)
        procs[0].send_signal(signal.SIGTERM)
        time_mod.sleep(0.3)
        procs[1].send_signal(signal.SIGTERM)
        out0 = "".join(lines0) + procs[0].communicate(timeout=180)[0]
        out1 = procs[1].communicate(timeout=180)[0]
    finally:
        for p in procs:
            p.kill()
    assert procs[0].returncode == 0, out0
    assert procs[1].returncode == 0, out1
    saved = []
    for out in (out0, out1):
        assert "preempted: checkpoint saved at step" in out, out
        saved.append(int(
            out.split("preempted: checkpoint saved at step")[1].split()[0]))
    # One COMMON step across the gang — the collective save completed.
    assert saved[0] == saved[1], (saved, out0[-2000:], out1[-2000:])
    assert saved[0] >= 3
    assert ckpt_lib.latest_step(ck) == saved[0]
