"""Paged KV cache tests: dense/paged byte-identity, zero-copy prefix
sharing (refcounts + CoW), block-leak freedom across retire/error
paths, memory-deferred admission, and the configurable stream timeout.

The contract under test is the serving one: the paged layout changes
WHERE K/V lives (block pool + per-slot tables instead of dense rows),
never WHAT is computed — greedy streams must match the dense layout
byte for byte, cold or warm, plain or chunked or speculative.
"""

import http.client
import time

import jax
import pytest

from kubeflow_tpu.observability.metrics import type_line
from kubeflow_tpu.serving.continuous import (
    ContinuousDecoder,
    StreamHandle,
    _Request,
)
from kubeflow_tpu.serving.engine import EngineConfig
from kubeflow_tpu.serving.server import ModelServer


@pytest.fixture(scope="module")
def model():
    from kubeflow_tpu.models.registry import get_model

    spec = get_model("lm-test-tiny")
    params = spec.init(jax.random.PRNGKey(0), spec.config)
    return spec, params


def _decoder(model, **kw):
    spec, params = model
    kw.setdefault("slots", 4)
    kw.setdefault("prefill_len", 32)
    kw.setdefault("max_new_tokens", 8)
    return ContinuousDecoder(params, spec.config, **kw)


def _paged(model, **kw):
    kw.setdefault("kv_layout", "paged")
    kw.setdefault("kv_block_size", 8)
    return _decoder(model, **kw)


# ---------------------------------------------------------------------------
# Layout byte-identity (the acceptance bar: paged changes cost, not output)
# ---------------------------------------------------------------------------


def test_dense_paged_greedy_byte_identical(model):
    prompts = [[1, 2, 3], [7, 5], [9, 9, 9, 9, 2], list(range(4, 28))]
    dense = _decoder(model)
    try:
        ref = [dense.generate(p, 6, timeout=120)["tokens"] for p in prompts]
    finally:
        dense.stop()
    paged = _paged(model)
    try:
        for p, r in zip(prompts, ref):
            assert paged.generate(p, 6, timeout=120)["tokens"] == r
        m = paged.metrics()
        assert m["kv_blocks_in_use"] == 0  # drained: every block freed
    finally:
        paged.stop()


def test_dense_paged_sampled_fixed_seed_identical(model):
    """Same seed, temperature>0: the RNG stream is consumed per decode
    round regardless of layout, so sampled outputs match too."""
    prompt = list(range(3, 19))

    def run(layout):
        d = (_paged if layout == "paged" else _decoder)(model, seed=7)
        try:
            return d.generate(prompt, 6, temperature=1.0,
                              timeout=120)["tokens"]
        finally:
            d.stop()

    assert run("paged") == run("dense")


def test_paged_chunked_and_speculative_greedy_parity(model):
    """decode_chunk and verify_chunk ride the same block pool: fused
    chunks and speculative verify must not change paged outputs."""
    prompts = [([3, 17, 29, 3, 17] * 3)[:12], [1, 2, 3]]
    plain = _paged(model)
    try:
        ref = [plain.generate(p, 8, timeout=120)["tokens"] for p in prompts]
    finally:
        plain.stop()
    chunked = _paged(model, chunk_size=4)
    try:
        for p, r in zip(prompts, ref):
            assert chunked.generate(p, 8, timeout=120)["tokens"] == r
    finally:
        chunked.stop()
    spec = _paged(model, speculative_k=3)
    try:
        for p, r in zip(prompts, ref):
            assert spec.generate(p, 8, timeout=120)["tokens"] == r
        assert spec.metrics()["kv_blocks_in_use"] == 0
    finally:
        spec.stop()


def test_paged_eos_parks_and_frees_blocks(model):
    probe = _paged(model)
    try:
        toks = probe.generate([1, 2, 3], 6, timeout=120)["tokens"]
    finally:
        probe.stop()
    eos = toks[2]
    d = _paged(model, eos_id=eos)
    try:
        res = d.generate([1, 2, 3], 6, timeout=120)
        assert res["tokens"] == toks[:3]
        assert res["finish_reason"] == "eos"
        assert d.metrics()["kv_blocks_in_use"] == 0
    finally:
        d.stop()


# ---------------------------------------------------------------------------
# Zero-copy prefix sharing: refcounted full blocks, CoW on partial tails
# ---------------------------------------------------------------------------


def test_warm_hit_block_aligned_shares_with_zero_copies(model):
    """A prefix covering whole blocks is shared purely by refcount:
    shared_blocks climbs, cow_copies stays 0, and the stream matches a
    cache-off decoder byte for byte."""
    donor = list(range(2, 26))            # 24 tokens = 3 full 8-blocks
    warm = donor + [100, 101, 102, 103]   # extends past the donor key
    off = _decoder(model)
    try:
        ref_donor = off.generate(donor, 6, timeout=120)["tokens"]
        ref_warm = off.generate(warm, 6, timeout=120)["tokens"]
    finally:
        off.stop()
    d = _paged(model, prefix_cache_slots=4, prefix_cache_min_len=8)
    try:
        assert d.generate(donor, 6, timeout=120)["tokens"] == ref_donor
        assert d.generate(warm, 6, timeout=120)["tokens"] == ref_warm
        m = d.metrics()
        assert m["prefix_hits"] == 1
        assert m["kv_shared_blocks"] == 3   # all three donor blocks
        assert m["kv_cow_copies"] == 0      # block-aligned: ZERO copies
        assert m["prefix_tokens_reused"] == 24
    finally:
        d.stop()


def test_cow_tail_never_mutates_donor_blocks(model):
    """A hit whose depth lands mid-block CoWs that one block; decoding
    the divergent stream must leave the donor's blocks intact — the
    donor's prompt replays byte-identically afterwards."""
    donor = list(range(2, 22))        # 20 tokens: 2 full blocks + 4 tail
    divergent = donor + [50, 51]
    off = _decoder(model)
    try:
        ref_donor = off.generate(donor, 6, timeout=120)["tokens"]
        ref_div = off.generate(divergent, 6, timeout=120)["tokens"]
    finally:
        off.stop()
    d = _paged(model, prefix_cache_slots=4, prefix_cache_min_len=8)
    try:
        cold = d.generate(donor, 6, timeout=120)["tokens"]
        assert cold == ref_donor
        assert d.generate(divergent, 6, timeout=120)["tokens"] == ref_div
        m = d.metrics()
        assert m["kv_cow_copies"] == 1      # exactly the tail block
        assert m["kv_shared_blocks"] == 2   # the two full blocks
        # Donor's blocks survived the CoW stream: replay is identical
        # (this admission hits the donor entry again and CoWs again).
        assert d.generate(donor, 6, timeout=120)["tokens"] == cold
    finally:
        d.stop()


def test_shared_blocks_visible_in_both_slots_with_refcounts(
        model, monkeypatch):
    """Two in-flight requests over a primed prefix hold the SAME
    physical blocks (trie ref + one per slot) while their owned tail
    blocks stay disjoint — the 'no aliasing unless refcounted-shared'
    invariant, inspected live. Decode steps are throttled so the
    scheduler can't retire the rows before the inspection."""
    import kubeflow_tpu.serving.continuous as cont

    real_step = cont.decode_step

    def slow_step(*a, **kw):
        time.sleep(0.25)
        return real_step(*a, **kw)

    monkeypatch.setattr(cont, "decode_step", slow_step)
    system = list(range(5, 29))  # 24 tokens = 3 blocks, aligned
    d = _paged(model, slots=2, prefix_cache_slots=4,
               prefix_cache_min_len=8)
    try:
        assert d.prime_prefix(system)
        h1 = d.submit(system + [100], 8)
        h2 = d.submit(system + [101], 8)
        it1, it2 = h1.tokens(timeout=120), h2.tokens(timeout=120)
        next(it1), next(it2)  # both admitted and mid-decode
        b0, b1 = d._slot_blocks[0], d._slot_blocks[1]
        shared = set(b0) & set(b1)
        assert len(shared) == 3
        for b in shared:
            # primed entry + two in-flight slots
            assert d._alloc.ref_count(b) == 3
        owned0, owned1 = set(b0) - shared, set(b1) - shared
        assert owned0 and owned1 and not (owned0 & owned1)
        for b in owned0 | owned1:
            assert d._alloc.ref_count(b) == 1
        for it in (it1, it2):
            for _ in it:
                pass
        # Drained: the primed entry holds its 3 blocks, and each
        # finished prompt's publish-on-finish kept one extra tail block
        # alive beyond the donor blocks it re-shares (zero copies, pure
        # refcounts).
        assert d.metrics()["kv_blocks_in_use"] == 5
    finally:
        d.stop()


def test_paged_prime_keeps_sampled_stream_identical(model):
    """prime_prefix writes blocks owned by the trie entry without
    touching the decode RNG: a primed paged decoder samples exactly like
    a cache-off dense decoder with the same seed."""
    system = list(range(3, 23))
    prompt = system + [200, 17, 11]

    def run(cache_on):
        if cache_on:
            d = _paged(model, seed=11, prefix_cache_slots=4,
                       prefix_cache_min_len=8)
        else:
            d = _decoder(model, seed=11)
        try:
            if cache_on:
                assert d.prime_prefix(system)
            return d.generate(prompt, 6, temperature=1.0,
                              timeout=120)["tokens"], d.metrics()
        finally:
            d.stop()

    off, _ = run(False)
    on, m = run(True)
    assert on == off
    assert m["prefix_hits"] == 1


# ---------------------------------------------------------------------------
# Leak freedom: error paths and memory-aware admission
# ---------------------------------------------------------------------------


def test_blocks_freed_after_loop_crash(model, monkeypatch):
    """A decode-loop death frees every block reference — in-flight,
    queued, and popped-but-unregistered admissions included."""
    d = _paged(model, slots=1)
    try:
        inflight = d.submit([1, 2, 3], 8)
        next(inflight.tokens(timeout=60))
        monkeypatch.setattr(
            "kubeflow_tpu.serving.continuous.decode_step",
            lambda *a, **k: (_ for _ in ()).throw(
                RuntimeError("injected decode failure")))
        queued = d.submit([4, 5], 4)
        with pytest.raises(RuntimeError, match="injected decode failure"):
            inflight.result(timeout=10)
        with pytest.raises(RuntimeError, match="injected decode failure"):
            queued.result(timeout=10)
        assert d.metrics()["kv_blocks_in_use"] == 0
    finally:
        d.stop()


def test_memory_deferred_admission_completes_everything(model):
    """A pool holding ONE worst-case sequence serializes admissions by
    memory, not slots: everything still completes FIFO, deferral is
    counted, and the pool drains to zero."""
    spec, params = model
    d = ContinuousDecoder(params, spec.config, slots=4, prefill_len=16,
                          max_new_tokens=8, kv_layout="paged",
                          kv_block_size=8, kv_pool_blocks=3)
    try:
        handles = [d.submit([i + 1] * 10, 8) for i in range(5)]
        outs = [h.result(timeout=120)["tokens"] for h in handles]
        assert all(len(o) == 8 for o in outs)
        m = d.metrics()
        assert m["kv_defer_admissions"] > 0
        assert m["peak_in_flight"] == 1  # 10+8 tokens = 3 blocks = pool
        assert m["kv_blocks_in_use"] == 0
    finally:
        d.stop()


def test_admission_pressure_reclaims_cached_prefix_blocks(model):
    """Cache-held blocks are reclaimable memory: when a new admission
    needs them, unpinned prefix entries are evicted rather than the
    request deferring forever."""
    spec, params = model
    d = ContinuousDecoder(params, spec.config, slots=2, prefill_len=16,
                          max_new_tokens=8, kv_layout="paged",
                          kv_block_size=8, kv_pool_blocks=3,
                          prefix_cache_slots=4, prefix_cache_min_len=8)
    try:
        # Finishing publishes the prompt's blocks into the trie, leaving
        # the pool fully claimed by the cache...
        first = d.generate([9] * 10, 8, timeout=120)
        assert d.metrics()["kv_blocks_in_use"] > 0
        # ...which the next admission reclaims by evicting the entry.
        second = d.generate([7] * 10, 8, timeout=120)
        assert len(first["tokens"]) == len(second["tokens"]) == 8
        assert d.metrics()["prefix_evictions"] >= 1
    finally:
        d.stop()


def test_want_zero_pure_prefill_frees_blocks(model):
    d = _paged(model)
    try:
        res = d.generate([5, 6, 7], 0, timeout=120)
        assert res["tokens"] == []
        assert res["prefill_logits"].shape == (256,)
        assert d.metrics()["kv_blocks_in_use"] == 0
    finally:
        d.stop()


def test_block_size_must_divide_total_len(model):
    spec, params = model
    with pytest.raises(ValueError, match="must divide"):
        ContinuousDecoder(params, spec.config, slots=2, prefill_len=16,
                          max_new_tokens=7, kv_layout="paged",
                          kv_block_size=8)


# ---------------------------------------------------------------------------
# Stream timeout plumbing + Prometheus export
# ---------------------------------------------------------------------------


def test_stream_handle_uses_decoder_default_timeout():
    req = _Request(tokens=[1], want=4, temperature=0.0)
    h = StreamHandle(req, default_timeout=0.05)
    t0 = time.perf_counter()
    with pytest.raises(TimeoutError):
        next(h.tokens())
    with pytest.raises(TimeoutError):
        h.result()
    assert time.perf_counter() - t0 < 5  # not the old hard-coded 60s


def test_decoder_threads_stream_timeout(model):
    """submit() hands the decoder's stream_timeout_s to every handle —
    the one knob replacing the hard-coded 60s."""
    d = _paged(model, stream_timeout_s=123.0)
    try:
        h = d.submit([1], 1)
        assert h._default_timeout == 123.0
        assert len(h.result(timeout=120)["tokens"]) == 1
    finally:
        d.stop()


def test_paged_counters_exported_as_prometheus(model):
    server = ModelServer(
        EngineConfig(model="lm-test-tiny", batch_size=4, max_seq_len=16,
                     max_new_tokens=8, kv_layout="paged", kv_block_size=8,
                     prefix_cache_slots=4, prefix_cache_min_len=8),
        port=0, grpc_port=None, batch_timeout_ms=2,
    )
    server.start()
    try:
        prompt = list(range(2, 18))
        for _ in range(2):  # second pass hits (and shares blocks)
            server.handle_predict("lm-test-tiny", {
                "instances": [{"tokens": prompt, "max_new_tokens": 3}],
            })
        conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                          timeout=30)
        conn.request("GET", "/monitoring/prometheus/metrics")
        text = conn.getresponse().read().decode()
        conn.close()
    finally:
        server.stop()
    assert "serving_kv_blocks_total 12" in text  # 4 slots * 24/8 blocks
    assert "serving_kv_blocks_in_use" in text
    assert type_line("serving_kv_shared_blocks_total", "counter") in text
    assert "serving_kv_cow_copies_total" in text
    assert "serving_kv_defer_admissions_total 0" in text


def test_concurrent_same_round_prefix_hits_stay_exact(model):
    """Regression (found by the fleet bench's shared-prefix traffic): a
    freed slot's block-table row must stay SENTINEL until the slot's
    own admission dispatch. Pointing it at freshly shared blocks at pop
    time let an earlier same-round hit admission's fused decode step
    write through the reassigned row at its stale device length —
    landing junk INSIDE refcount-shared prefix blocks, silently
    corrupting every stream that read the donor prefix afterwards.
    Three followers hitting the same donor concurrently (admitted in
    one round, slots freshly recycled) is the trigger."""
    from concurrent.futures import ThreadPoolExecutor

    prefix = [(7 * j) % 97 + 3 for j in range(24)]
    followers = [prefix + [200, 150 + r, 11 + r, 7] for r in (1, 2, 3)]
    gen = 8

    cold = _paged(model, slots=8, max_new_tokens=gen)
    try:
        ref = [cold.generate(t, gen, timeout=120)["tokens"]
               for t in followers]
    finally:
        cold.stop()

    d = _paged(model, slots=8, max_new_tokens=gen,
               prefix_cache_slots=8, prefix_cache_min_len=16,
               prefill_len_buckets=2, kv_pool_blocks=40,
               stream_timeout_s=120.0)
    try:
        # Leader decodes (recycling slots + publishing the prefix),
        # then all three followers hit the donor in one burst.
        d.generate(prefix + [200, 150, 11, 7], gen, timeout=120)
        with ThreadPoolExecutor(3) as pool:
            out = list(pool.map(
                lambda t: d.generate(t, gen, timeout=120)["tokens"],
                followers))
        m = d.metrics()
    finally:
        d.stop()
    assert m["prefix_hits"] == 3
    assert out == ref  # byte-identical to the no-cache reference
