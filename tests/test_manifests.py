"""Manifest golden tests — the analogue of the reference's jsonnet unit tests
(std.assertEqual against golden objects, e.g.
kubeflow/tf-training/tests/tf-job_test.jsonnet:14-60, runner
testing/test_jsonnet.py).

Structural invariants are asserted inline; full golden YAML snapshots live in
tests/golden/ and are compared byte-for-byte (regenerate with
`python -m kubeflow_tpu.manifests.snapshot --update`).
"""

import os

import pytest
import yaml

from kubeflow_tpu.apis import jobs as jobs_api
from kubeflow_tpu.manifests import all_prototypes, generate
from kubeflow_tpu.manifests.core import PrototypeError

GOLDEN_DIR = os.path.join(os.path.dirname(__file__), "golden")


def test_all_prototypes_registered():
    protos = all_prototypes()
    for expected in [
        "training-operator",
        "jax-job-simple",
        "tf-job",
        "pytorch-job",
        "mpi-job",
        "mxnet-job",
        "chainer-job",
        "gateway",
        "centraldashboard",
        "tpu-serving",
        "inference-service",
        "experiment",
    ]:
        assert expected in protos, f"missing prototype {expected}"


def test_unknown_param_rejected():
    with pytest.raises(PrototypeError, match="unknown params"):
        generate("gateway", {"bogus": 1})


def test_missing_required_param_rejected():
    with pytest.raises(PrototypeError, match="missing required"):
        generate("tpu-serving", {})


def test_training_operator_objects():
    objs = generate("training-operator", {})
    kinds = [o["kind"] for o in objs]
    # all six job CRDs
    assert kinds.count("CustomResourceDefinition") == len(jobs_api.ALL_JOB_KINDS)
    assert "Deployment" in kinds and "ServiceAccount" in kinds
    assert "ClusterRole" in kinds and "ClusterRoleBinding" in kinds
    crd_names = {
        o["metadata"]["name"] for o in objs if o["kind"] == "CustomResourceDefinition"
    }
    assert "jaxjobs.kubeflow-tpu.org" in crd_names
    assert "tfjobs.kubeflow-tpu.org" in crd_names
    # RBAC covers the job resources + status subresources
    role = next(o for o in objs if o["kind"] == "ClusterRole")
    resources = role["rules"][0]["resources"]
    assert "jaxjobs" in resources and "jaxjobs/status" in resources


def test_training_operator_namespace_scoped_rbac():
    objs = generate("training-operator", {"cluster_scoped": False})
    kinds = [o["kind"] for o in objs]
    assert "Role" in kinds and "RoleBinding" in kinds
    assert "ClusterRole" not in kinds


def test_jax_job_simple_shape():
    (job,) = generate(
        "jax-job-simple",
        {"name": "smoke", "num_workers": 4, "accelerator": "v5litepod-16", "topology": "4x4"},
    )
    jobs_api.validate_job(job)
    assert job["kind"] == "JaxJob"
    assert job["spec"]["replicaSpecs"]["Worker"]["replicas"] == 4
    assert job["spec"]["tpu"]["topology"] == "4x4"
    res = job["spec"]["replicaSpecs"]["Worker"]["template"]["spec"]["containers"][0][
        "resources"
    ]
    assert res["limits"][jobs_api.TPU_RESOURCE] == 4


def test_compat_job_prototypes_validate():
    cases = {
        "tf-job": {"name": "t", "num_ps": 2},
        "pytorch-job": {"name": "p"},
        "mpi-job": {"name": "m"},
        "mxnet-job": {"name": "x"},
        "chainer-job": {"name": "c"},
    }
    for proto, params in cases.items():
        (job,) = generate(proto, params)
        jobs_api.validate_job(job)


def test_tpu_serving_surface():
    objs = generate("tpu-serving", {"name": "bert", "model_path": "gs://b/m", "num_tpu_chips": 4})
    dep = next(o for o in objs if o["kind"] == "Deployment")
    svc = next(o for o in objs if o["kind"] == "Service")
    c = dep["spec"]["template"]["spec"]["containers"][0]
    ports = {p["name"]: p["containerPort"] for p in c["ports"]}
    assert ports == {"grpc": 9000, "rest": 8500}
    assert c["livenessProbe"]["tcpSocket"]["port"] == 9000
    assert c["resources"]["limits"][jobs_api.TPU_RESOURCE] == 4
    annotations = dep["spec"]["template"]["metadata"]["annotations"]
    assert annotations["prometheus.io/scrape"] == "true"
    svc_ports = {p["name"]: p["port"] for p in svc["spec"]["ports"]}
    assert svc_ports == {"grpc": 9000, "rest": 8500}
    assert "kubeflow-tpu.org/gateway-route" in svc["metadata"]["annotations"]


def test_gateway_objects():
    objs = generate("gateway", {"replicas": 2})
    dep = next(o for o in objs if o["kind"] == "Deployment")
    assert dep["spec"]["replicas"] == 2
    # gateway needs RBAC to list services for route discovery
    role = next(o for o in objs if o["kind"] == "ClusterRole")
    assert role["rules"][0]["resources"] == ["services"]


def test_job_validation_rejects_bad_specs():
    (job,) = generate("jax-job-simple", {"name": "j"})
    bad = yaml.safe_load(yaml.safe_dump(job))
    bad["spec"]["replicaSpecs"]["Evaluator"] = bad["spec"]["replicaSpecs"]["Worker"]
    with pytest.raises(jobs_api.JobValidationError, match="replica type"):
        jobs_api.validate_job(bad)

    (job2,) = generate("pytorch-job", {"name": "p"})
    job2["spec"]["replicaSpecs"]["Master"]["replicas"] = 3
    with pytest.raises(jobs_api.JobValidationError, match="at most 1"):
        jobs_api.validate_job(job2)


def test_golden_snapshots():
    """Byte-for-byte golden comparison for every prototype snapshot on disk."""
    if not os.path.isdir(GOLDEN_DIR):
        pytest.skip("no golden dir")
    from kubeflow_tpu.manifests.snapshot import SNAPSHOT_CASES, render_case

    for case_name in SNAPSHOT_CASES:
        path = os.path.join(GOLDEN_DIR, f"{case_name}.yaml")
        assert os.path.exists(path), (
            f"missing golden {path}; run python -m kubeflow_tpu.manifests.snapshot --update"
        )
        with open(path) as f:
            golden = f.read()
        assert render_case(case_name) == golden, (
            f"golden drift for {case_name}; regenerate with "
            "python -m kubeflow_tpu.manifests.snapshot --update and review the diff"
        )


def test_inference_server_prototype():
    from kubeflow_tpu.manifests.core import generate

    objs = generate("inference-server", {
        "name": "triton", "image": "nvcr.io/tritonserver:latest",
        "port": 8000, "num_tpu_chips": 4,
    })
    dep = [o for o in objs if o["kind"] == "Deployment"][0]
    c = dep["spec"]["template"]["spec"]["containers"][0]
    assert c["resources"]["limits"]["google.com/tpu"] == 4
    svc = [o for o in objs if o["kind"] == "Service"][0]
    ann = svc["metadata"]["annotations"]
    assert "kubeflow-tpu.org/gateway-route" in ann
    assert ann["prometheus.io/scrape"] == "true"


def test_storage_prototypes():
    from kubeflow_tpu.manifests.core import generate

    objs = generate("nfs-volume", {"server": "10.0.0.5"})
    pv = [o for o in objs if o["kind"] == "PersistentVolume"][0]
    assert pv["spec"]["nfs"]["server"] == "10.0.0.5"
    claim = [o for o in objs if o["kind"] == "PersistentVolumeClaim"][0]
    assert claim["spec"]["volumeName"] == pv["metadata"]["name"]
    assert generate("checkpoint-pvc", {})[0]["spec"]["accessModes"] == [
        "ReadWriteMany"
    ]
