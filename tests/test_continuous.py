"""Continuous-batching decode tests: lockstep parity, early return for
short requests, token streaming over chunked REST and gRPC streams, EOS.

The reference's serving tests stop at TF-Serving RPC smoke checks
(testing/test_tf_serving.py); these additionally pin the scheduler's
correctness against the one-shot compiled path.
"""

import http.client
import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.models.decode import generate
from kubeflow_tpu.models.registry import get_model
from kubeflow_tpu.serving.continuous import ContinuousDecoder
from kubeflow_tpu.serving.engine import EngineConfig
from kubeflow_tpu.serving.server import ModelServer


@pytest.fixture(scope="module")
def model():
    spec = get_model("lm-test-tiny")
    params = spec.init(jax.random.PRNGKey(0), spec.config)
    return spec, params


@pytest.fixture()
def decoder(model):
    spec, params = model
    d = ContinuousDecoder(params, spec.config, slots=4, prefill_len=16,
                          max_new_tokens=8)
    yield d
    d.stop()


def test_greedy_parity_with_lockstep_generate(model, decoder):
    """Greedy decoding through the continuous scheduler must produce the
    same tokens as the one-shot compiled ``generate`` call."""
    spec, params = model
    prompts = [[1, 2, 3], [7, 5], [9, 9, 9, 9, 2]]
    want = 6

    b = len(prompts)
    t0 = max(len(p) for p in prompts)
    toks = np.zeros((b, t0), np.int32)
    lengths = np.zeros((b,), np.int32)
    for i, p in enumerate(prompts):
        toks[i, : len(p)] = p
        lengths[i] = len(p)
    ref, _last = generate(
        params, jnp.asarray(toks), jnp.asarray(lengths), spec.config,
        max_new_tokens=want, key=jax.random.PRNGKey(0),
        temperature=jnp.zeros((b,)),
    )
    ref = np.asarray(ref)

    handles = [decoder.submit(p, want) for p in prompts]
    for i, h in enumerate(handles):
        res = h.result(timeout=60)
        assert res["tokens"] == ref[i].tolist(), f"prompt {i} diverged"
        assert res["finish_reason"] == "length"


def test_short_request_returns_before_long_peer(decoder):
    """The decoupling the lockstep batch lacks: a 1-token request submitted
    WITH a long one finishes as soon as its own token lands."""
    long_h = decoder.submit([1, 2, 3], 8)
    next(long_h.tokens(timeout=60))  # long is mid-flight
    short_h = decoder.submit([4, 5], 1)
    short_res = short_h.result(timeout=60)
    long_running_at_short_done = not long_h._req.done.is_set()
    long_res = long_h.result(timeout=60)
    assert len(short_res["tokens"]) == 1
    assert len(long_res["tokens"]) == 8
    assert long_running_at_short_done


def test_tokens_stream_incrementally(decoder):
    h = decoder.submit([3, 1], 5)
    seen = list(h.tokens(timeout=60))
    assert len(seen) == 5
    assert h.result(timeout=5)["tokens"] == seen


def test_slot_reuse_beyond_capacity(model):
    """More requests than slots: the queue drains as rows free up, and a
    reused slot must not leak the previous occupant's cache."""
    spec, params = model
    d = ContinuousDecoder(params, spec.config, slots=2, prefill_len=16,
                          max_new_tokens=8)
    try:
        solo = d.submit([2, 4, 6], 4).result(timeout=60)
        handles = [d.submit([2, 4, 6], 4) for _ in range(5)]
        for h in handles:
            assert h.result(timeout=60)["tokens"] == solo["tokens"]
    finally:
        d.stop()


def test_eos_frees_slot_early(model):
    spec, params = model
    probe = ContinuousDecoder(params, spec.config, slots=2, prefill_len=16,
                              max_new_tokens=8)
    try:
        toks = probe.generate([1, 2, 3], 6)["tokens"]
    finally:
        probe.stop()
    eos = toks[2]  # the third greedy token becomes the stop id
    d = ContinuousDecoder(params, spec.config, slots=2, prefill_len=16,
                          max_new_tokens=8, eos_id=eos)
    try:
        res = d.generate([1, 2, 3], 6)
        assert res["tokens"] == toks[:3]
        assert res["finish_reason"] == "eos"
    finally:
        d.stop()


def test_want_zero_returns_prefill_logits(decoder):
    res = decoder.generate([5, 6, 7], 0)
    assert res["tokens"] == []
    assert res["prefill_logits"].shape == (256,)


# ---------------------------------------------------------------------------
# Server surfaces
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def server():
    s = ModelServer(
        EngineConfig(model="lm-test-tiny", batch_size=4, max_seq_len=16,
                     max_new_tokens=8),
        port=0, grpc_port=0, batch_timeout_ms=2,
    )
    s.start()
    yield s
    s.stop()


def _post_json(port, path, payload):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    conn.request("POST", path, body=json.dumps(payload).encode(),
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    body = resp.read()
    conn.close()
    return resp.status, json.loads(body)


def test_rest_stream_chunked(server):
    """`"stream": true` returns chunked JSON lines, one per token, with the
    first record arriving before the generation completes."""
    conn = http.client.HTTPConnection("127.0.0.1", server.port, timeout=60)
    conn.request(
        "POST", "/v1/models/lm-test-tiny:predict",
        body=json.dumps({"stream": True, "instances": [
            {"tokens": [1, 2, 3], "max_new_tokens": 6},
        ]}).encode(),
        headers={"Content-Type": "application/json"},
    )
    resp = conn.getresponse()
    assert resp.status == 200
    assert resp.getheader("Content-Type") == "application/jsonlines"
    records = []
    buf = b""
    while True:
        chunk = resp.read1(65536)
        if not chunk:
            break
        buf += chunk
        while b"\n" in buf:
            line, buf = buf.split(b"\n", 1)
            if line.strip():
                records.append(json.loads(line))
    conn.close()
    tokens = [r["token"] for r in records if "token" in r]
    final = records[-1]
    assert final["done"] and final["tokens"] == tokens
    assert len(tokens) == 6
    assert final["ttft_ms"] >= 0

    # Non-streamed request over the same server agrees (greedy).
    status, out = _post_json(
        server.port, "/v1/models/lm-test-tiny:predict",
        {"instances": [{"tokens": [1, 2, 3], "max_new_tokens": 6}]},
    )
    assert status == 200
    assert out["predictions"][0]["tokens"] == tokens


def test_rest_stream_validation_fails_before_headers(server):
    status, body = _post_json(
        server.port, "/v1/models/lm-test-tiny:predict",
        {"stream": True, "instances": [{"tokens": [1]},
                                       {"tokens": [2]}]},
    )
    assert status == 400
    assert "exactly one instance" in body["error"]


def test_grpc_stream(server):
    import grpc

    from kubeflow_tpu.serving.grpc_server import stream_stub

    with grpc.insecure_channel(f"127.0.0.1:{server.grpc_port}") as chan:
        do_stream = stream_stub(chan)
        records = list(do_stream(
            "lm-test-tiny", {"tokens": [4, 4], "max_new_tokens": 4}
        ))
    tokens = [r["token"] for r in records if "token" in r]
    assert len(tokens) == 4
    assert records[-1]["done"] and records[-1]["tokens"] == tokens


def test_mixed_generation_and_predict_instances(server):
    """One request mixing a generation and a plain predict: the generation
    rides the continuous decoder, the predict rides the batcher, and both
    come back in order."""
    status, out = _post_json(
        server.port, "/v1/models/lm-test-tiny:predict",
        {"instances": [
            {"tokens": [1, 2, 3], "max_new_tokens": 3},
            {"tokens": [1, 2, 3]},
        ]},
    )
    assert status == 200
    gen, plain = out["predictions"]
    assert len(gen["tokens"]) == 3
    assert len(plain["logits"]) == 256
    # Greedy first generated token == the plain predict's argmax.
    assert gen["next_token"] == plain["next_token"]


def test_decoder_metrics_exposed(server):
    # The generation tests above drove the decoder; counters must show it.
    conn = http.client.HTTPConnection("127.0.0.1", server.port, timeout=30)
    conn.request("GET", "/monitoring/prometheus/metrics")
    text = conn.getresponse().read().decode()
    conn.close()
    assert "serving_tokens_emitted_total" in text
    assert "serving_ttft_avg_seconds" in text


def test_sustained_mixed_lengths_all_complete(model):
    """A burst of ragged-length requests through a small-slot decoder all
    complete with their own lengths (continuous admission under churn)."""
    spec, params = model
    d = ContinuousDecoder(params, spec.config, slots=3, prefill_len=16,
                          max_new_tokens=8)
    try:
        t0 = time.perf_counter()
        wants = [1, 8, 2, 5, 3, 8, 1, 4]
        handles = [d.submit([i + 1], w) for i, w in enumerate(wants)]
        for h, w in zip(handles, wants):
            assert len(h.result(timeout=120)["tokens"]) == w
        assert time.perf_counter() - t0 < 120
    finally:
        d.stop()


# ---------------------------------------------------------------------------
# Chunked decode (K steps fused per device dispatch — high-RTT-link mode)
# ---------------------------------------------------------------------------


def test_chunked_greedy_parity(model):
    """chunk_size>1 fuses K steps into one dispatch but must emit exactly
    the tokens the per-step path emits."""
    spec, params = model
    prompts = [[1, 2, 3], [7, 5], [9, 9, 9, 9, 2]]
    per_step = ContinuousDecoder(params, spec.config, slots=4,
                                 prefill_len=16, max_new_tokens=8)
    try:
        ref = [per_step.generate(p, 6)["tokens"] for p in prompts]
    finally:
        per_step.stop()
    chunked = ContinuousDecoder(params, spec.config, slots=4,
                                prefill_len=16, max_new_tokens=8,
                                chunk_size=4)
    try:
        handles = [chunked.submit(p, 6) for p in prompts]
        for h, r in zip(handles, ref):
            assert h.result(timeout=60)["tokens"] == r
        # The fused path must actually batch: 18 tokens emitted in far
        # fewer device round-trips than the per-token path's one-per-step
        # (admission rounds ramp with a single un-fused step for TTFT).
        assert chunked.dispatches < chunked.steps
    finally:
        chunked.stop()


def test_chunked_eos_parks_on_device(model):
    """EOS inside a fused chunk stops the row on device: the request
    finishes with reason 'eos' and no post-EOS tokens leak."""
    spec, params = model
    probe = ContinuousDecoder(params, spec.config, slots=2, prefill_len=16,
                              max_new_tokens=8)
    try:
        toks = probe.generate([1, 2, 3], 6)["tokens"]
    finally:
        probe.stop()
    eos = toks[2]  # third greedy token becomes the stop id (mid-chunk)
    d = ContinuousDecoder(params, spec.config, slots=2, prefill_len=16,
                          max_new_tokens=8, eos_id=eos, chunk_size=4)
    try:
        res = d.generate([1, 2, 3], 6)
        assert res["tokens"] == toks[:3]
        assert res["finish_reason"] == "eos"
        # Slot freed by the parking: a follow-up request reuses it cleanly.
        assert d.generate([1, 2, 3], 2)["tokens"] == toks[:2]
    finally:
        d.stop()


def test_sustained_arrivals_keep_chunking_engaged(model):
    """Under sustained arrivals (pending non-empty nearly every round) the
    TTFT ramp must not degrade chunked dispatch back to one dispatch per
    token (ADVICE r4): un-fused ramp rounds are never consecutive, so
    u <= c + 1 where u/c are un-fused/chunked dispatch counts."""
    spec, params = model
    K = 4
    d = ContinuousDecoder(params, spec.config, slots=2, prefill_len=16,
                          max_new_tokens=8, chunk_size=K)
    try:
        long_req = d.submit([1, 2, 3], 8)
        it = long_req.tokens(timeout=60)
        next(it)  # long request admitted and past its ramp round
        shorts = [d.submit([5 + i], 1) for i in range(6)]
        for h in shorts:
            assert len(h.result(timeout=60)["tokens"]) == 1
        assert len(long_req.result(timeout=60)["tokens"]) == 8
        # Ramp steps ride the admission dispatch; the streak cap bounds
        # admission-ONLY rounds (no chunk) so chunking stays engaged:
        # never two in a row => ramp_rounds <= chunk dispatches + 1.
        m = d.metrics()
        assert m["ramp_rounds"] <= m["decode_dispatches"] + 1, m
    finally:
        d.stop()


def test_batched_admission_parity_and_dispatch_count(model):
    """A burst admitted together (one prefill + one insert dispatch)
    produces exactly the tokens sequential admission produces, and the
    admission cost is 2 dispatches per ROUND, not per request."""
    spec, params = model
    prompts = [[1, 2, 3], [7, 5], [9, 9, 9, 9, 2], [4]]
    ref_d = ContinuousDecoder(params, spec.config, slots=1, prefill_len=16,
                              max_new_tokens=8)
    try:
        # slots=1 forces one-at-a-time admission — the sequential oracle.
        ref = [ref_d.generate(p, 6)["tokens"] for p in prompts]
    finally:
        ref_d.stop()

    d = ContinuousDecoder(params, spec.config, slots=4, prefill_len=16,
                          max_new_tokens=8)
    try:
        handles = [d.submit(p, 6) for p in prompts]
        for h, r in zip(handles, ref):
            assert h.result(timeout=60)["tokens"] == r
        m = d.metrics()
        assert m["requests_admitted"] == 4
        # Fused admission: ONE dispatch per admission round (usually one
        # round for the whole burst) — far below the 8 of per-request
        # prefill+insert pairs.
        assert m["prefill_dispatches"] <= 3
    finally:
        d.stop()


def test_batched_admission_mixed_wants_and_pure_prefill(model):
    """A batch mixing normal requests with want=0 pure prefills: the
    prefills return logits immediately, the rest decode to completion."""
    spec, params = model
    d = ContinuousDecoder(params, spec.config, slots=4, prefill_len=16,
                          max_new_tokens=8)
    try:
        probe = d.submit([1, 2, 3], 2)
        score = d.submit([5, 6], 0)        # pure prefill
        long = d.submit([7], 8)
        r_score = score.result(timeout=60)
        assert r_score["tokens"] == []
        assert r_score["prefill_logits"] is not None
        assert len(probe.result(timeout=60)["tokens"]) == 2
        assert len(long.result(timeout=60)["tokens"]) == 8
        # Same logits as a solo prefill of the same prompt.
        solo = d.submit([5, 6], 0).result(timeout=60)
        np.testing.assert_allclose(r_score["prefill_logits"],
                                   solo["prefill_logits"], rtol=2e-5,
                                   atol=2e-5)
    finally:
        d.stop()


# ---------------------------------------------------------------------------
# Decode-loop crash propagation (no stream may hang out its timeout)
# ---------------------------------------------------------------------------


def test_loop_crash_fails_inflight_and_queued_promptly(model, monkeypatch):
    """If the decode loop dies, every live StreamHandle — mid-decode AND
    still queued — must get the error immediately, not a 60s timeout."""
    spec, params = model
    d = ContinuousDecoder(params, spec.config, slots=1, prefill_len=16,
                          max_new_tokens=8)
    try:
        inflight = d.submit([1, 2, 3], 8)
        next(inflight.tokens(timeout=60))  # decoding is underway
        boom = RuntimeError("injected decode failure")

        def explode(*_a, **_k):
            raise boom

        monkeypatch.setattr("kubeflow_tpu.serving.continuous.decode_step",
                            explode)
        queued = d.submit([4, 5], 4)  # slots=1: this one sits in _pending
        t0 = time.perf_counter()
        with pytest.raises(RuntimeError, match="injected decode failure"):
            inflight.result(timeout=10)
        with pytest.raises(RuntimeError, match="injected decode failure"):
            queued.result(timeout=10)
        assert time.perf_counter() - t0 < 5  # propagated, not timed out
        with pytest.raises(RuntimeError, match="stopped"):
            d.submit([1], 1)  # the dead decoder refuses new work clearly
    finally:
        d.stop()


def test_loop_crash_during_admission_fails_popped_requests(model,
                                                           monkeypatch):
    """A request popped from the queue but not yet registered in a slot
    when admission blows up must still be failed (it is visible to
    neither the slot sweep nor the pending deque)."""
    spec, params = model
    d = ContinuousDecoder(params, spec.config, slots=2, prefill_len=16,
                          max_new_tokens=8)
    try:
        monkeypatch.setattr(
            "kubeflow_tpu.serving.continuous.admit_rows_and_step",
            lambda *a, **k: (_ for _ in ()).throw(
                RuntimeError("injected admission failure")))
        h = d.submit([1, 2, 3], 4)
        with pytest.raises(RuntimeError, match="injected admission"):
            h.result(timeout=10)
    finally:
        d.stop()


def test_stream_iteration_raises_loop_error(model, monkeypatch):
    """tokens() consumers (the streaming REST/gRPC paths) see the crash
    as a raised error on the iterator, not a silent stall."""
    spec, params = model
    d = ContinuousDecoder(params, spec.config, slots=2, prefill_len=16,
                          max_new_tokens=8)
    try:
        h = d.submit([1, 2, 3], 8)
        it = h.tokens(timeout=60)
        next(it)
        monkeypatch.setattr(
            "kubeflow_tpu.serving.continuous.decode_step",
            lambda *a, **k: (_ for _ in ()).throw(
                RuntimeError("injected decode failure")))
        with pytest.raises(RuntimeError, match="injected decode failure"):
            for _ in it:
                pass
    finally:
        d.stop()


def test_chunked_mixed_lengths_all_complete(model):
    spec, params = model
    d = ContinuousDecoder(params, spec.config, slots=3, prefill_len=16,
                          max_new_tokens=8, chunk_size=4)
    try:
        wants = [1, 8, 2, 5, 3, 8]
        handles = [d.submit([i + 1], w) for i, w in enumerate(wants)]
        for h, w in zip(handles, wants):
            assert len(h.result(timeout=120)["tokens"]) == w
    finally:
        d.stop()


def test_metrics_snapshot_consistent_under_load(model):
    """PR-11 regression (tpu-lint lock-inconsistent-guard): several
    counters (steps, prefix_misses, prefix_inserts, queue depth) were
    mutated outside the metrics lock while metrics() snapshotted under
    it — torn reads, the PR-4 bug class. Hammer metrics() from a side
    thread during live traffic and assert the snapshots stay sane."""
    import threading

    spec, params = model
    d = ContinuousDecoder(params, spec.config, slots=4, prefill_len=16,
                          max_new_tokens=8, prefix_cache_slots=4,
                          prefix_cache_min_len=4, kv_layout="paged",
                          kv_block_size=4)
    errors: list[Exception] = []
    stop = threading.Event()

    def hammer():
        last_steps = 0
        try:
            while not stop.is_set():
                m = d.metrics()
                # Monotone under the lock-guarded snapshot; a torn
                # read could observe a lost update going backwards.
                assert m["decode_steps"] >= last_steps
                last_steps = m["decode_steps"]
                assert m["queued"] >= 0
                assert m["prefill_tokens"] >= 0
        except Exception as e:  # noqa: BLE001 — surfaced below
            errors.append(e)

    t = threading.Thread(target=hammer, daemon=True)
    t.start()
    try:
        handles = [d.submit([1 + i, 2, 3, 4, 5], 6) for i in range(12)]
        for h in handles:
            h.result(timeout=60)
    finally:
        stop.set()
        t.join(timeout=10)
        d.stop()
    assert not errors, errors


def test_stop_with_queued_requests_fails_them_cleanly(model):
    """PR-11 regression: stop() iterated the live pending deque after a
    bounded join — racing the scheduler's popleft. It now snapshots the
    queue under the cv; every queued request still gets its terminal
    error."""
    spec, params = model
    d = ContinuousDecoder(params, spec.config, slots=2, prefill_len=16,
                          max_new_tokens=8)
    handles = [d.submit([1, 2, 3], 8) for _ in range(6)]
    d.stop()
    for h in handles:
        with pytest.raises((RuntimeError, TimeoutError)):
            h.result(timeout=5)
