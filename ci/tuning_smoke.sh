#!/bin/sh
# CI tuning-smoke (ci/pipeline.yaml `tuning-smoke` stage): the self-tuning
# engine must close its loop end-to-end on CPU. Each leg runs one full
# Experiment per policy through the REAL ExperimentController on the fake
# apiserver (kubeflow_tpu/tuning/sweep.py) and exits nonzero when any gate
# trips: non-Succeeded experiment, non-monotone best-so-far trace, no
# improvement over the checked-in defaults (trial 0 is always the
# baseline), missing promotion record, or — on the two-policy leg — the
# bayesian proposer needing more than half of random's trials to reach
# random's final best.
set -e

check_json() {
    printf '%s\n' "$1" | python -c '
import json, sys
text = sys.stdin.read()
start = text.find("{")
if start < 0:
    sys.exit("tuning sweep emitted no JSON")
rec = json.loads(text[start:])  # non-JSON output fails here
if rec.get("regression"):
    reasons = rec.get("reasons")
    sys.exit(f"tuning sweep regression marker set: {reasons}")
for policy, r in rec["policies"].items():
    state = r.get("state")
    if state != "Succeeded":
        sys.exit(f"{policy} experiment ended {state}")
    trace = r.get("bestSoFarTrace") or []
    if not trace or any(b < a for a, b in zip(trace, trace[1:])):
        sys.exit(f"{policy} best-so-far trace missing or not monotone: {trace}")
    if not r.get("improvementPercent") or r["improvementPercent"] <= 0:
        sys.exit(f"{policy} found nothing better than the defaults")
    if not (r.get("promotion") or {}).get("version"):
        sys.exit(f"{policy} promotion not recorded")
'
}

# Leg 1 — search economy on the deterministic synthetic landscape:
# random (the economy baseline) then GP-EI bayesian; the sweep gates
# bayesian reaching random's final best in <= half the trials, every
# policy beating the defaults, monotone traces, and a recorded
# promotion (versions write onto the fake target InferenceService).
out="$(JAX_PLATFORMS=cpu python -m kubeflow_tpu.tuning.sweep \
    --scenario synthetic-knobs --policies random,bayesianoptimization \
    --trials 12 --seed 7 --promote)"
check_json "$out"
echo "tuning smoke: synthetic-knobs economy gate ok"

# Leg 2 — the real engine: decode-tps runs live ContinuousDecoder
# trials (steady-state timed pass after an untimed warm pass over the
# same trace) and must find a knob setting that beats the checked-in
# DECODE_TPS_DEFAULTS, then record the winner's promotion.
out="$(JAX_PLATFORMS=cpu python -m kubeflow_tpu.tuning.sweep \
    --scenario decode-tps --policies bayesianoptimization \
    --trials 6 --seed 3 --promote)"
check_json "$out"
echo "tuning smoke: decode-tps beats defaults ok"
echo "tuning smoke ok"
