#!/bin/sh
# CI bench-smoke (ci/pipeline.yaml `bench-smoke` stage): the serving-path
# perf plumbing must keep emitting valid JSON with no regression marker.
# Runs on CPU (the tiny presets) — this guards the measurement machinery
# and the prefix-cache parity/volume invariants, not absolute numbers.
set -e

check_json() {
    printf '%s\n' "$1" | python -c '
import json, sys
lines = [ln for ln in sys.stdin.read().splitlines() if ln.strip()]
if not lines:
    sys.exit("bench emitted no output")
rec = json.loads(lines[-1])  # non-JSON output fails here
if rec.get("regression"):
    sys.exit(f"bench regression marker set: {rec}")
if rec.get("kv_blocks_in_use_after_drain", 0) != 0:
    sys.exit(f"paged KV pool leaked blocks after drain: {rec}")
'
}

out="$(JAX_PLATFORMS=cpu python bench_serving.py --quick --generate)"
check_json "$out"
out="$(JAX_PLATFORMS=cpu python bench_serving.py --quick --prefix-reuse)"
check_json "$out"
# Speculative decoding: the marker fires on non-identical greedy outputs
# (speculation may only change cost, never tokens) or on <=1.5 accepted
# tokens per verify dispatch in the draft-model run.
out="$(JAX_PLATFORMS=cpu python bench_serving.py --quick --speculative)"
check_json "$out"
# Paged KV: the marker fires on dense/paged greedy divergence, on a
# paged in-flight peak below 2x dense at equal pool bytes, or on a
# block leak after drain (kv_blocks_in_use must return to 0).
out="$(JAX_PLATFORMS=cpu python bench_serving.py --quick --concurrency-sweep)"
check_json "$out"
# Int8 KV + fused block-table attention: the marker fires when int8
# sustains <1.8x the fp in-flight peak at equal pool bytes, when fp
# blocks are not bitwise-identical to dense, when int8/fused greedy
# tokens fall outside the pinned tolerance, when the fused decode path
# traces a dense KV gather (the materialization it exists to remove),
# when it falls below the gather baseline's tokens/s, or on a leak.
out="$(JAX_PLATFORMS=cpu python bench_serving.py --quick --kv-dtype-sweep)"
check_json "$out"
# Fleet serving: the marker fires when 4 replicas at equal per-replica
# pool bytes sustain <3.4x the single replica's aggregate tokens/s on
# shared-prefix traffic, when prefix-affine routing fails to beat
# seeded-random routing's per-replica prefix hit rate strictly, when
# greedy tokens differ across runs, or when any replica leaks blocks.
out="$(JAX_PLATFORMS=cpu python bench_serving.py --quick --fleet-sweep)"
check_json "$out"
# Disaggregated prefill/decode: the marker fires when the role-split
# fleet's TTFT p99 beats colocated by <1.3x at equal total pool bytes
# under mixed burst traffic, when aggregate tokens/s falls under 0.95x
# colocated, when greedy tokens differ from the single-replica
# reference (fp or int8 — scale blocks must ride the handoff exactly),
# or when either pool leaks blocks.
out="$(JAX_PLATFORMS=cpu python bench_serving.py --quick --disagg-sweep)"
check_json "$out"
# Multi-tenant QoS + tiered KV: the marker fires when high-priority
# TTFT p99 improves by <1.5x over FIFO at equal HBM under overloaded
# two-tenant traffic, when any stream (including each suspended-and-
# resumed one) is not byte-identical to the undisturbed reference,
# when a low-priority request starves (not all complete), when the
# host tier's second chance never fires (no hit-after-evict or no
# cold-prefill reduction vs the no-tier baseline), or when the device
# pool leaks blocks / the host tier leaks pinned bytes after drain.
out="$(JAX_PLATFORMS=cpu python bench_serving.py --quick --qos-sweep)"
check_json "$out"
# Long-context serving: the marker fires when a prompt 4x the dense
# prefill window fails to admit through bounded chunks byte-identically
# (greedy AND sampled) to a monolithic wide-window reference, when one
# token past max_prompt_len is not a clean PromptTooLong (413), when
# decode streams fail to progress during a chunked admission or their
# inter-token gap p99 exceeds 1.5x the no-prefill baseline, or on a
# block leak after drain.
out="$(JAX_PLATFORMS=cpu python bench_serving.py --quick --long-context-sweep)"
check_json "$out"
# Model-parallel serving: the marker fires when greedy tokens differ
# across tp=1/2/4 mesh shapes at equal total pool bytes (including
# shared-prefix block sharing + CoW and the int8 scale-carrying leg),
# when a tp=2 export fails to import byte-identically into a tp=1 pool
# through the JSON envelope, when the sharded engine's throughput
# collapses (CPU aggregate retention < 0.6x; per-chip >= 0.8x gates on
# real chips), or on leaked blocks.
out="$(JAX_PLATFORMS=cpu python bench_serving.py --quick --tp-sweep)"
check_json "$out"
# Live weight streaming: the marker fires when a live swap drops or
# errors any in-flight stream, when the swap stall exceeds one
# decode-dispatch gap at p99, when post-swap greedy tokens differ from
# a decoder cold-started on the pushed weights (fp, int8, tp=2), when
# the RL loop's rollout throughput under per-step live pushes falls
# under 5x the restart-per-update baseline at equal hardware, or on
# leaked blocks.
out="$(JAX_PLATFORMS=cpu python bench_serving.py --quick --weight-push-sweep)"
check_json "$out"
# Progressive delivery: the marker fires when a healthy candidate
# fails to walk 1%->100% and promote (fleet left on mixed epochs or
# serving weights that differ from a cold start on the candidate), or
# when a TTFT-regressed candidate fails to auto-roll-back from Shadow
# with gate-breach evidence and byte-identical post-rollback streams
# vs the incumbent cold decoder.
out="$(JAX_PLATFORMS=cpu python bench_serving.py --quick --rollout-sweep)"
check_json "$out"
# Fleet KV economy: the marker fires when the distributed prefix cache
# (shared directory + peer pulls + cold content-addressed tier) fails
# to cut follower-phase prefill volume AND TTFT p99 below the private-
# per-replica-cache baseline at equal warm-tier bytes under the
# spill-heavy seeded-random trace, when any leg's greedy tokens differ
# from the uncached reference, when no peer/cold import happened, when
# a weight push landing mid-pull is not refused as stale, or when any
# leg leaks blocks in any tier.
out="$(JAX_PLATFORMS=cpu python bench_serving.py --quick --kv-economy-sweep)"
check_json "$out"
# Flash-crowd elasticity: the marker fires when peer-weight birth plus
# a warm compile cache fails to reach >=5x cold-to-first-token vs the
# checkpoint-restore + cold-compile baseline, when the peer-pulled
# pytree differs byte-for-byte from the checkpoint restore or a
# post-rollout pull returns a stale epoch's bytes, when predictive
# scale-to-N under the storm fails to keep TTFT p99 under the
# reactive +1-per-period ladder's, when probe tokens diverge between
# birth paths, or on leaked blocks after drain.
out="$(JAX_PLATFORMS=cpu python bench_serving.py --quick --flash-crowd-sweep)"
check_json "$out"
echo "bench smoke ok"
# Training input pipeline: prefetch-on must match prefetch-off final
# loss byte-for-byte (bench.py sets the regression marker otherwise)
# and the stall accounting must ride the driver-facing line.
out="$(JAX_PLATFORMS=cpu python bench.py --quick --steps 6)"
check_json "$out"
printf '%s\n' "$out" | python -c '
import json, sys
rec = json.loads([ln for ln in sys.stdin.read().splitlines()
                  if ln.strip()][-1])
for key in ("train_input_stall_pct", "train_input_stall_off_pct",
            "train_pipeline_speedup"):
    if key not in rec:
        sys.exit(f"bench output missing {key}: {rec}")
'
echo "train pipeline smoke ok"
# Elastic training: grow 4->8 and shrink 8->4 mid-run through the real
# loop's reshard point. The marker fires when any post-reshard loss
# differs from the undisturbed restore-into-target reference at the
# same global batch (live reshard must equal the rescale path it
# replaces, byte-for-byte), or when the shrink downtime fails to beat
# the kill-path floor (sync save + restore + step rebuild) for the
# same capacity release.
out="$(JAX_PLATFORMS=cpu python bench.py --elastic --steps 12)"
check_json "$out"
printf '%s\n' "$out" | python -c '
import json, sys
rec = json.loads([ln for ln in sys.stdin.read().splitlines()
                  if ln.strip()][-1])
for key in ("elastic_reshard_grow_ms", "elastic_reshard_shrink_ms",
            "elastic_kill_resume_ms", "elastic_shrink_vs_kill_speedup"):
    if key not in rec:
        sys.exit(f"bench output missing {key}: {rec}")
if rec["elastic_shrink_vs_kill_speedup"] <= 1.0:
    sys.exit(f"shrink not strictly better than kill-requeue-resume: {rec}")
'
echo "elastic reshard smoke ok"
