#!/bin/sh
# CI exposition lint (ci/pipeline.yaml `metrics-lint` stage): boot every
# /metrics surface in-process — model server (decoder driven), gateway
# admin, availability prober, operator HealthServer (with one real
# scheduling round driven so the scheduler_* decision families carry
# samples and their names are asserted present) — scrape each over
# real HTTP, and validate TYPE lines, label escaping and histogram
# bucket ordering with the pure-python promtool-style checker. Exactly
# one renderer (kubeflow_tpu/observability/metrics.py) may know the
# exposition text format; this stage is what keeps a fifth hand-rolled
# renderer from creeping back in.
set -e

JAX_PLATFORMS=cpu python -m kubeflow_tpu.observability.lint --self-check

# The single-renderer invariant, checked at the AST level by the
# tpu-lint exposition checker (kubeflow_tpu/analysis/exposition.py):
# no "# TYPE" string literal outside the allowed renderer modules —
# every exporter must go through the shared renderer, and tests assert
# via its type_line(). The AST scan replaces the old grep: it sees
# through f-strings and concatenation, and it cannot be fooled by the
# phrase appearing in comments or docs. Scope matches the old gate
# (package + tests + benches); the full rule suite over kubeflow_tpu/
# runs in the separate static-analysis stage.
# tests/*.py (not tests/fixtures/ — the analysis bad-fixtures contain
# a deliberate hand-rolled renderer the checker suite asserts on).
JAX_PLATFORMS=cpu python -m kubeflow_tpu.analysis \
    --rules metrics-type-literal \
    kubeflow_tpu tests/*.py bench.py bench_serving.py
echo "single-renderer invariant ok"
