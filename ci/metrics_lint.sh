#!/bin/sh
# CI exposition lint (ci/pipeline.yaml `metrics-lint` stage): boot every
# /metrics surface in-process — model server (decoder driven), gateway
# admin, availability prober, operator HealthServer (with one real
# scheduling round driven so the scheduler_* decision families carry
# samples and their names are asserted present) — scrape each over
# real HTTP, and validate TYPE lines, label escaping and histogram
# bucket ordering with the pure-python promtool-style checker. Exactly
# one renderer (kubeflow_tpu/observability/metrics.py) may know the
# exposition text format; this stage is what keeps a fifth hand-rolled
# renderer from creeping back in.
set -e

JAX_PLATFORMS=cpu python -m kubeflow_tpu.observability.lint --self-check

# The grep-able single-renderer invariant: no "# TYPE" string literal
# anywhere outside observability/metrics.py (every exporter must go
# through the shared renderer, and tests assert via its type_line()).
offenders="$(grep -rl '# TYPE' kubeflow_tpu tests bench.py bench_serving.py \
    --include='*.py' | grep -v 'observability/metrics.py' || true)"
if [ -n "$offenders" ]; then
    echo "exposition renderer leaked outside observability/metrics.py:"
    echo "$offenders"
    exit 1
fi
echo "single-renderer invariant ok"
