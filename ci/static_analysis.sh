#!/bin/sh
# CI static-analysis gate (ci/pipeline.yaml `static-analysis` stage):
# run the tpu-lint semantic checkers (kubeflow_tpu/analysis — lock
# discipline, thread lifecycle, resource pairing, JAX hygiene, metrics
# exposition) over the whole package against the checked-in baseline.
#
# The run fails on ANY non-baselined finding, and — the ratchet — on
# any baseline entry that no longer fires (stale entries must be
# deleted, so the baseline only ever shrinks). Suppressions in source
# (`# tpu-lint: disable=<rule> -- <reason>`) require a reason; a
# reason-less one is itself a finding. See docs/static-analysis.md.
set -e

python -m kubeflow_tpu.analysis kubeflow_tpu/ \
    --baseline ci/tpu_lint_baseline.json

echo "static analysis ok"
